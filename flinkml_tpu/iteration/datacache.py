"""Segmented host data cache + epoch replay + prefetching device feed.

Parity (SURVEY.md §2.2): the reference's ``iteration/datacache/nonkeyed``
package — ``DataCacheWriter`` (append-only segments of serialized records,
``DataCacheWriter.java:36-139``), ``DataCacheReader`` (iterator with
position, ``DataCacheReader.java:35-135``), ``Segment{path,count,size}``
(``Segment.java:27``), ``DataCacheSnapshot`` (persist/recover segment lists
into checkpoint streams, ``DataCacheSnapshot.java:1-224``) — and the
``ReplayOperator`` (``operator/ReplayOperator.java:62-250``) that caches a
data stream in epoch 0 and re-emits it every subsequent epoch.

TPU-native redesign: records are columnar *batches* (dict of numpy arrays),
not serialized rows. A batch lives in host RAM until the writer's memory
budget is exceeded, then spills to a segment file — a raw little-endian
columnar format (JSON header + contiguous column bytes) that reads back via
``np.fromfile`` with zero deserialization per record. Epoch replay is an
iterator over batches; the ``PrefetchingDeviceFeed`` overlaps the next
batch's host→HBM ``jax.device_put`` with the current step's compute, which
is the whole point: the reference replays through the JVM record-at-a-time,
we replay at memcpy/PCIe speed and the TPU never waits for input.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import shutil
import threading
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("datacache")

Batch = Dict[str, np.ndarray]

_MAGIC = b"FMLTSEG1"


@dataclasses.dataclass(frozen=True)
class Segment:
    """One spilled segment file. Parity: ``Segment.java:27`` {path,count,size}."""

    path: str
    num_rows: int
    nbytes: int


def _write_segment(path: str, batch: Batch) -> Segment:
    """Raw columnar segment: MAGIC | u32 header_len | JSON header | column bytes.

    Columns are written C-contiguous back to back; the header records
    (dtype, shape, byte offset) per column. Atomic via temp-file rename so a
    crash mid-spill never leaves a half segment in a manifest.
    """
    header: Dict[str, Any] = {"columns": {}}
    offset = 0
    cols: List[Tuple[str, np.ndarray]] = []
    for name, arr in batch.items():
        arr = np.ascontiguousarray(arr)
        header["columns"][name] = {
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "offset": offset,
        }
        offset += arr.nbytes
        cols.append((name, arr))
    num_rows = cols[0][1].shape[0] if cols else 0
    header["num_rows"] = num_rows
    hbytes = json.dumps(header).encode()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(hbytes).to_bytes(4, "little"))
        f.write(hbytes)
        for _, arr in cols:
            # tofile writes straight from the (already contiguous) buffer —
            # no tobytes() copy at the moment memory is tightest.
            arr.tofile(f)
    os.replace(tmp, path)
    return Segment(path=path, num_rows=num_rows, nbytes=offset)


def _read_segment(path: str) -> Batch:
    with open(path, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise IOError(f"{path}: not a datacache segment (magic={magic!r})")
        hlen = int.from_bytes(f.read(4), "little")
        header = json.loads(f.read(hlen))
        data_start = f.tell()
        batch: Batch = {}
        for name, meta in header["columns"].items():
            dtype = np.dtype(meta["dtype"])
            shape = tuple(meta["shape"])
            f.seek(data_start + meta["offset"])
            count = int(np.prod(shape)) if shape else 1
            batch[name] = np.fromfile(f, dtype=dtype, count=count).reshape(shape)
    return batch


class DataCacheWriter:
    """Append columnar batches; spill to disk beyond a memory budget.

    Parity: ``DataCacheWriter.java:36-139`` (append-only segments, finished
    by ``finish()``). The reference always spills (its cache exists to
    replay between epochs of a streaming job); here small datasets stay in
    RAM and only the overflow hits disk.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        memory_budget_bytes: Optional[int] = None,
    ):
        if directory is None and memory_budget_bytes is not None:
            raise ValueError(
                "memory_budget_bytes requires a spill directory; without one "
                "the cache is RAM-only and the budget cannot be honored"
            )
        self.directory = directory
        self.memory_budget_bytes = int(
            256 << 20 if memory_budget_bytes is None else memory_budget_bytes
        )
        # Ordered: each entry is an in-RAM Batch or a spilled Segment, in
        # append order — a mid-stream spill must not reorder replay.
        self._entries: List[Any] = []
        self._mem_bytes = 0
        self._num_spilled = 0
        self._finished = False
        self._num_rows = 0

    def append(self, batch: Batch) -> None:
        if self._finished:
            raise RuntimeError("DataCacheWriter already finished")
        batch = {k: np.asarray(v) for k, v in batch.items()}
        nbytes = sum(a.nbytes for a in batch.values())
        rows = next(iter(batch.values())).shape[0] if batch else 0
        for name, a in batch.items():
            if a.dtype == object:
                # Fail at ingestion, not later mid-spill/mid-snapshot.
                raise TypeError(
                    f"column {name!r} has dtype=object; densify before caching"
                )
            if a.shape[0] != rows:
                raise ValueError(
                    f"column {name!r} has {a.shape[0]} rows, expected {rows}"
                )
        self._num_rows += rows
        if (
            self.directory is not None
            and self._mem_bytes + nbytes > self.memory_budget_bytes
        ):
            # Spilled batches are copied to disk and re-read fresh each
            # epoch; the caller's arrays stay untouched (and reusable).
            self._spill(batch)
        else:
            # RAM-resident batches are handed back by reference on every
            # epoch; freeze them so in-place mutation — by a consumer or by
            # the producer reusing its buffer — fails loudly instead of
            # silently corrupting later epochs.
            for a in batch.values():
                a.flags.writeable = False
            self._entries.append(batch)
            self._mem_bytes += nbytes

    def _spill(self, batch: Batch) -> None:
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, f"segment-{self._num_spilled:06d}.bin")
        self._num_spilled += 1
        segment = _write_segment(path, batch)
        _log.info(
            "datacache spill: %d in-RAM bytes over the %d-byte budget; "
            "segment %s (%d rows, %d bytes) spilled to disk",
            self._mem_bytes, self.memory_budget_bytes, path,
            segment.num_rows, segment.nbytes,
        )
        self._entries.append(segment)

    def finish(self) -> "DataCache":
        """Seal the cache; no further appends. Returns the readable cache."""
        self._finished = True
        return DataCache(entries=list(self._entries), num_rows=self._num_rows)


@dataclasses.dataclass
class DataCache:
    """A sealed, re-readable sequence of batches (RAM-resident + spilled),
    in original append order."""

    entries: List[Any]  # Batch | Segment, append-ordered
    num_rows: int

    @property
    def num_batches(self) -> int:
        return len(self.entries)

    @property
    def mem_batches(self) -> List[Batch]:
        return [e for e in self.entries if not isinstance(e, Segment)]

    @property
    def segments(self) -> List[Segment]:
        return [e for e in self.entries if isinstance(e, Segment)]

    def reader(self, start_position: int = 0) -> "DataCacheReader":
        return DataCacheReader(self, start_position)

    def __iter__(self) -> Iterator[Batch]:
        return self.reader()


class DataCacheReader:
    """Iterate batches with a resumable position.

    Parity: ``DataCacheReader.java:35-135`` (iterator + position for
    checkpoint alignment). ``position`` counts whole batches consumed, so a
    resumed reader re-reads from the next batch boundary.
    """

    def __init__(self, cache: DataCache, start_position: int = 0):
        self._cache = cache
        self.position = int(start_position)

    def __iter__(self) -> "DataCacheReader":
        return self

    def __next__(self) -> Batch:
        i = self.position
        if i >= len(self._cache.entries):
            raise StopIteration
        self.position += 1
        entry = self._cache.entries[i]
        if isinstance(entry, Segment):
            return _read_segment(entry.path)
        # Shallow copy: consumers may add/replace dict keys without altering
        # the cached batch; the arrays themselves are frozen at append().
        return dict(entry)


class DataCacheSnapshot:
    """Persist/recover a cache for checkpoint-resume.

    Parity: ``DataCacheSnapshot.java:1-224`` (segment lists into checkpoint
    raw-state streams + local-FS copy). Persisting forces RAM-resident
    batches into segment files under ``snapshot_dir`` and writes a JSON
    manifest; recovery rebuilds a fully disk-backed cache.
    """

    MANIFEST = "datacache-manifest.json"

    @staticmethod
    def persist(cache: DataCache, snapshot_dir: str) -> None:
        os.makedirs(snapshot_dir, exist_ok=True)
        segments: List[Segment] = []
        for i, entry in enumerate(cache.entries):
            if isinstance(entry, Segment):
                dst = os.path.join(snapshot_dir, f"snap-segment-{i:06d}.bin")
                if os.path.abspath(dst) != os.path.abspath(entry.path):
                    shutil.copyfile(entry.path, dst)
                segments.append(Segment(dst, entry.num_rows, entry.nbytes))
            else:
                path = os.path.join(snapshot_dir, f"snap-segment-{i:06d}.bin")
                segments.append(_write_segment(path, entry))
        manifest = {
            "num_rows": cache.num_rows,
            "segments": [
                {"file": os.path.basename(s.path), "num_rows": s.num_rows, "nbytes": s.nbytes}
                for s in segments
            ],
        }
        tmp = os.path.join(snapshot_dir, f".manifest.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(snapshot_dir, DataCacheSnapshot.MANIFEST))

    @staticmethod
    def recover(snapshot_dir: str) -> DataCache:
        with open(os.path.join(snapshot_dir, DataCacheSnapshot.MANIFEST)) as f:
            manifest = json.load(f)
        segments = [
            Segment(
                path=os.path.join(snapshot_dir, s["file"]),
                num_rows=s["num_rows"],
                nbytes=s["nbytes"],
            )
            for s in manifest["segments"]
        ]
        return DataCache(entries=list(segments), num_rows=manifest["num_rows"])


# ---------------------------------------------------------------------------
# Epoch replay (ReplayOperator analog)
# ---------------------------------------------------------------------------

def cache_stream(
    batches: Iterable[Batch],
    directory: Optional[str] = None,
    memory_budget_bytes: Optional[int] = None,
) -> DataCache:
    """Materialize a one-shot batch stream into a replayable cache.

    This is epoch 0 of ``ReplayOperator.java:62-250`` (cache *and* forward);
    iterate the returned cache for every subsequent epoch.
    """
    w = DataCacheWriter(directory, memory_budget_bytes)
    for b in batches:
        w.append(b)
    return w.finish()


def replay(cache: DataCache, num_epochs: Optional[int] = None) -> Iterator[Tuple[int, Batch]]:
    """Yield ``(epoch, batch)`` re-reading the whole cache once per epoch.

    Parity: ``ReplayOperator``'s re-emission of all cached records with the
    new epoch on every global alignment; here the "alignment" is just the
    outer loop advancing. ``num_epochs=None`` replays forever (the caller's
    termination criterion breaks the loop).
    """
    if cache.num_batches == 0:
        return  # an endless replay of nothing would spin forever
    epoch = 0
    while num_epochs is None or epoch < num_epochs:
        for batch in cache.reader():
            yield epoch, batch
        epoch += 1


# ---------------------------------------------------------------------------
# Prefetching device feed
# ---------------------------------------------------------------------------

_FEED_END = object()


def _feed_worker(batches: Iterable[Any], place, q: "queue.Queue",
                 stop: threading.Event, err_box: list) -> None:
    """The feed's producer loop — a module-level function on purpose: it
    must hold NO reference back to the feed object, so a consumer that
    abandons iteration and drops its handle leaves the feed
    garbage-collectable, and the feed's GC finalizer (which sets
    ``stop``) releases this thread instead of leaking it."""

    def put(item) -> bool:
        # Abort-aware blocking put: must not be dropped when the queue is
        # momentarily full (a consumer would then block forever), and must
        # not block after close()/GC (the timed put re-checks ``stop``).
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    try:
        for b in batches:
            if stop.is_set():
                return  # abandoned/closed — don't pay the next transfer
            if not put(place(b)):
                return  # closed while blocked — drop and exit
    except BaseException as e:  # surfaced (with traceback) on next()
        err_box.append(e)
    finally:
        put(_FEED_END)


class PrefetchingDeviceFeed:
    """Background host→device transfer pipeline over a batch iterator.

    A worker thread pulls host batches, applies ``place`` (default
    ``jax.device_put``, or a mesh-sharded placement like
    ``mesh.shard_batch``) and parks up to ``depth`` device-resident batches
    in a queue. With ``depth>=2`` the next batch's PCIe/DMA transfer runs
    under the current step's compute — the TPU analog of the reference's
    credit-based network buffering, minus the network.

    Lifecycle: the feed is a context manager; ``close()`` (idempotent)
    stops the worker and drains the queue. A consumer that abandons
    iteration WITHOUT closing does not leak the worker — the worker
    holds no reference to the feed, so dropping the handle lets GC run a
    finalizer that stops it. A raising producer parks its exception and
    every subsequent ``next()`` re-raises it with the producer's
    original traceback.
    """

    _END = _FEED_END  # kept for callers/tests that referenced it

    def __init__(self, batches: Iterable[Any], place=None, depth: int = 2,
                 thread_name: str = "device-feed"):
        import jax
        import weakref

        self._place = place if place is not None else jax.device_put
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._err_box: list = []
        self._stop = threading.Event()
        self._done = False

        self._thread = threading.Thread(
            target=_feed_worker,
            args=(batches, self._place, self._q, self._stop, self._err_box),
            daemon=True,
            name=thread_name,
        )
        self._thread.start()
        self._finalizer = weakref.finalize(self, self._stop.set)

    def __iter__(self) -> "PrefetchingDeviceFeed":
        return self

    def __next__(self):
        if self._done:
            if self._err_box:
                raise self._err_box[0]
            raise StopIteration
        item = self._q.get()
        if item is _FEED_END:
            self._done = True  # later next() must not block on an empty queue
            if self._err_box:
                raise self._err_box[0]
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the worker and release queued device batches. Idempotent."""
        self._stop.set()
        self._done = True  # next() after close() must not block
        # Drain until the worker exits: its timed put() observes _stop within
        # one timeout tick, so no put can block forever (review finding: a
        # single drain raced with an in-flight put and leaked the thread).
        while self._thread.is_alive():
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self) -> "PrefetchingDeviceFeed":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
