"""Multi-process streamed (out-of-core) training: the agreement layer.

The streamed fits replay a host-side :class:`~flinkml_tpu.iteration.
datacache.DataCache` through per-batch SPMD steps (``shard_map`` +
``psum`` over the full mesh). On a single process the host is free to
dispatch whatever batch shapes and step counts it likes; on a
multi-process mesh SPMD imposes two global invariants the reference got
for free from Flink's partitioned-stream runtime (every subtask of an
operator runs the same dataflow over its own partition,
``AllReduceImpl.java:52-299`` aligns per-chunk contributions):

1. **Same program, same shapes** — every process must dispatch the same
   compiled step at every loop index, so the per-process batch height
   must be one agreed constant (padded, zero-weighted rows are exact
   no-ops).
2. **Same step count** — a process whose local cache is shorter must keep
   dispatching (zero-weight "dummy" steps) until the longest process has
   drained, or the collective wedges.

This module provides those agreements: a device-mediated scalar max
(:func:`agree_max` — rides the same ICI/DCN fabric as the data plane,
like :func:`~flinkml_tpu.parallel.distributed.host_barrier`), the
per-epoch :class:`SyncedReplayPlan` that wraps a local cache reader into
an agreed-length, fixed-shape batch sequence, and a pooled reservoir
sample (:func:`pooled_sample`) for trainers whose initialization draws
rows from the global dataset (KMeans, GMM).

Convention (documented in ``docs/development/parallelism.md``): on a
multi-process mesh each process feeds its OWN partition of the stream —
the reference's per-subtask stream partitions — typically its
:func:`~flinkml_tpu.parallel.process_slice` of a global dataset. The
fitted model is identical on every process (replicated outputs, host
updates applied to identical values).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.iteration.datacache import DataCache, Segment
from flinkml_tpu.parallel.mesh import DeviceMesh
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("stream_sync")


@functools.lru_cache(maxsize=128)
def _agree_fn(mesh, axis: str, op: str):
    """Compiled collective for :func:`_device_agree`, cached per
    (mesh, op) — a fresh closure per call would defeat the jit cache and
    recompile every agreement (a streamed fit performs ~10 of them)."""
    red = {"max": jax.lax.pmax, "sum": jax.lax.psum,
           "min": jax.lax.pmin}[op]

    def _one(x):
        return red(x, axis)

    return jax.jit(
        jax.shard_map(_one, mesh=mesh, in_specs=P(axis), out_specs=P(None))
    )


def _device_agree(value: int, mesh: Optional[DeviceMesh], op: str) -> int:
    """Device-mediated int32 reduction of a per-process scalar across all
    processes (``op`` in {"max", "sum"}). Single-process: returns ``value``.

    Construction mirrors ``parallel.distributed.host_barrier``: each
    process fills only its addressable shards of a data-axis-sharded
    vector with its value; one collective makes the reduction visible to
    every host. No side channel, no extra service.
    """
    if jax.process_count() == 1:
        return int(value)
    dm = mesh if mesh is not None else DeviceMesh()
    axis = dm.axis_names[0]
    sharding = jax.sharding.NamedSharding(dm.mesh, P(axis))
    global_shape = (dm.axis_size(),)
    full = np.full(global_shape, int(value), dtype=np.int32)
    arr = jax.make_array_from_callback(
        global_shape, sharding, lambda idx: full[idx]
    )
    reduced = _agree_fn(dm.mesh, axis, op)(arr)
    return int(np.asarray(reduced.addressable_shards[0].data)[0])


def agree_max(value: int, mesh: Optional[DeviceMesh] = None) -> int:
    """Max of a per-process int across all processes (see module doc).

    Values must fit int32 (schedule lengths, batch heights, dtype codes —
    all small by construction). For unbounded quantities like global row
    counts, use :func:`gather_vectors` (f64-exact transport) instead.
    """
    return _device_agree(value, mesh, "max")


def agree_min(value: int, mesh: Optional[DeviceMesh] = None) -> int:
    """Min of a per-process int across all processes — the agreement a
    set of elastic survivors uses to pick the newest COMMONLY-valid
    snapshot (each nominates its local newest; the min is the newest
    every survivor can restore). Same int32 transport caveats as
    :func:`agree_max`."""
    return _device_agree(value, mesh, "min")


def agree_all_ok(ok: bool, mesh: Optional[DeviceMesh], what: str) -> None:
    """Agreed validation barrier: raise on EVERY process when any process
    failed a local check.

    A rank-local ``raise`` in a multi-process code path is a distributed
    hang, not an error: the raising rank exits while its peers block
    forever in their next collective (the Gloo backend wedges
    permanently). So every local validation that can fail on one rank
    but not another must funnel through this rendezvous before any rank
    proceeds — all ranks call it at the same point, and all ranks raise
    together. Single-process: raises immediately when not ``ok``.
    """
    if jax.process_count() == 1:
        failed = not ok
    else:
        failed = _device_agree(0 if ok else 1, mesh, "max") != 0
    if failed:
        suffix = "" if ok else " (failed on this process)"
        _log.error("agreed abort: %s failed on at least one process%s",
                   what, suffix)
        raise ValueError(
            f"{what} failed on at least one process{suffix}; "
            "all ranks abort together to avoid a distributed hang"
        )
    elif jax.process_count() > 1:
        _log.info("rendezvous ok: %s agreed on all %d processes",
                  what, jax.process_count())


class DeferredValidation:
    """Collect local ingest-time errors, then rendezvous.

    Ingest validation (batch shapes, zero weights, label domains) fails
    on ONE rank's data — raising there immediately would strand the
    peers in their next collective (see :func:`agree_all_ok`). Instead
    the ingest loop holds the FIRST failure, skips the remaining items
    (a partial cache is fine — it is never consumed), and
    :meth:`rendezvous` agrees the outcome across all ranks BEFORE any
    planning collective — re-raising the ORIGINAL error on the failing
    rank and the generic agreement error elsewhere. Rendezvous-first
    matters: skip-on-failure can leave every local cache empty, and a
    plan built first would mask the real error as "stream is empty on
    every process".
    """

    def __init__(self):
        self.err: Optional[Exception] = None

    def call(self, fn, *args):
        """Run an ingest step that RETURNS values (extraction +
        validation fused); returns None once a failure is held.

        The caller must SKIP its accumulation (reservoir adds, moment
        sums, cache appends) on a None return: accumulating a batch that
        failed validation — or any batch after one — can itself raise
        rank-locally (e.g. adding a ragged batch to a fixed-width
        reservoir), which is exactly the hang class this class exists to
        prevent. A partial cache/accumulation is fine: the rendezvous
        aborts every rank before the result is consumed."""
        if self.err is not None:
            return None
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — held, re-raised later
            self.err = e
            return None

    def rendezvous(self, mesh: Optional[DeviceMesh], what: str) -> None:
        try:
            agree_all_ok(self.err is None, mesh, what)
        except ValueError:
            if self.err is not None:
                raise self.err
            raise


def agreed_restore(manager, epoch, like, mesh: Optional[DeviceMesh],
                   what: Optional[str] = None):
    """Checkpoint restore with the rank-local-failure agreement protocol.

    A corrupt or unreadable checkpoint on ONE rank's view of the shared
    FS must abort EVERY rank — a rank-local raise strands the peers in
    the training collectives (the hang class :func:`agree_all_ok`
    documents). One definition for every streamed trainer's resume path
    so the protocol cannot drift per estimator. Single-process, the
    original error re-raises immediately."""
    dv = DeferredValidation()
    got = dv.call(manager.restore, epoch, like)
    dv.rendezvous(mesh, what or f"checkpoint restore (epoch {epoch})")
    return got


def agreed_restore_latest(manager, like, mesh: Optional[DeviceMesh],
                          what: str = "checkpoint restore (latest)"):
    """:func:`agreed_restore` over ``manager.restore_latest``. A
    post-rendezvous ``None`` means genuinely no checkpoint (a held
    failure raises at the rendezvous instead)."""
    dv = DeferredValidation()
    got = dv.call(manager.restore_latest, like)
    dv.rendezvous(mesh, what)
    return got


def guarded_iter(batches, dv: DeferredValidation):
    """Iterate a source whose ``next()`` itself can raise rank-locally
    (an IOError reading this rank's shard, a raising generator) — fold
    the failure into ``dv`` and END the stream instead of propagating,
    so the caller still reaches the post-loop rendezvous in lockstep
    with its peers. Also stops early once ``dv`` holds any error: there
    is no point pulling more local data for a fit that is agreed to
    abort. Pair with :meth:`DeferredValidation.call` for the loop body;
    multi-process ingest loops should use both (or just
    :func:`checked_ingest`, which composes them)."""
    it = iter(batches)
    while dv.err is None:
        try:
            item = next(it)
        except StopIteration:
            return
        except Exception as e:  # noqa: BLE001 — held for the rendezvous
            dv.err = e
            return
        yield item


def checked_ingest(source, dv: DeferredValidation, fn, multi: bool):
    """THE multi-process-safe ingest loop, shared by every streamed
    trainer's pass 0: run ``fn`` (extraction + validation + any cache
    append / accumulation that depends on the validated invariants) over
    ``source``, yielding its non-None results.

    Multi-process, both the source iterator's own raises
    (:func:`guarded_iter`) and ``fn``'s raises
    (:meth:`DeferredValidation.call`) are held for the caller's
    ``dv.rendezvous`` — and once an error is held the remaining items
    are skipped, so accumulation after a failed invariant can never
    raise rank-locally. Single-process, failures propagate immediately
    at the offending item."""
    if not multi:
        for item in source:
            out = fn(item)
            if out is not None:
                yield out
        return
    for item in guarded_iter(source, dv):
        out = dv.call(fn, item)
        if out is not None:
            yield out


def agree_feature_dim(
    cache: DataCache,
    column: str,
    mesh: Optional[DeviceMesh],
    local_dim: int = 0,
) -> int:
    """Discover + agree the feature dim of a cached stream across
    processes (one definition for every streamed trainer).

    ``local_dim`` short-circuits discovery when the trainer already knows
    it; otherwise the first cached batch's ``column`` is read. An empty
    local cache contributes 0 and adopts the agreed dim. A mismatch
    raises on EVERY rank (see :func:`agree_all_ok`).
    """
    if not local_dim and cache.num_batches:
        reader = cache.reader()
        local_dim = int(np.asarray(next(iter(reader))[column]).shape[1])
        if hasattr(reader, "close"):
            reader.close()
    dim = agree_max(local_dim, mesh)
    agree_all_ok(
        not (local_dim and local_dim != dim), mesh,
        f"feature-dim agreement (local {local_dim}, global {dim})",
    )
    return dim


def entry_rows(entry: Any) -> int:
    """Row count of one sealed-cache entry (RAM dict or spilled
    Segment) — the public metadata hook schedule agreements are built
    from (``SyncedReplayPlan.create``; ALS's chunk-level schedule)."""
    if isinstance(entry, Segment):
        return entry.num_rows
    return next(iter(entry.values())).shape[0] if entry else 0


_entry_rows = entry_rows  # backward-compatible private alias


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def agree_first_item_dim(source, check, dim_of, mesh):
    """First-item feature-dim agreement for UNCACHED lockstep streams
    (PCA's single pass, the online trainers): pull the first item with
    iterator raises HELD, validate it, agree the dim across processes,
    and return ``(first, rest, dim)`` — the caller chains
    ``[first] + rest`` into :func:`synced_stream`. An exhausted rank
    returns ``first=None`` and adopts the agreed dim (it will feed only
    zero-weight dummies); an empty GLOBAL stream raises on every rank,
    as does a dim mismatch or any held failure (original error on the
    failing rank). One definition so the three uncached trainers cannot
    drift (the cached-stream variant is :func:`agree_feature_dim`)."""
    it = iter(source)
    first = None
    held = None
    try:
        first = next(it, None)
    except Exception as e:  # noqa: BLE001 — agreed below
        held = e
    local_d = 0
    if first is not None and held is None:
        try:
            check(first)
            local_d = int(dim_of(first))
        except Exception as e:  # noqa: BLE001 — agreed below
            held = e
    dim = agree_max(local_d, mesh)
    try:
        agree_all_ok(
            held is None and not (local_d and local_d != dim), mesh,
            f"feature-dim agreement (local {local_d}, global {dim})",
        )
    except ValueError:
        if held is not None:
            raise held
        raise
    if dim == 0:
        raise ValueError("training stream is empty on every process")
    return first, it, dim


@dataclasses.dataclass
class SyncedReplayPlan:
    """The agreed per-epoch replay schedule for one sealed local cache.

    ``global_steps`` — dispatches every process performs per epoch;
    ``local_height`` — fixed padded row count each process contributes per
    step (the global batch is ``local_height × process_count`` rows).
    """

    global_steps: int
    local_height: int
    mesh: DeviceMesh

    @staticmethod
    def create(
        cache: DataCache, mesh: DeviceMesh, row_tile: int
    ) -> "SyncedReplayPlan":
        """Agree the schedule for ``cache`` (this process's partition).

        ``row_tile`` is the divisibility unit for the local height
        (usually ``mesh.axis_size() * 8`` — also divisible by the local
        device count, so :meth:`DeviceMesh.global_batch` placement works).
        An empty local cache is legal (that process only feeds dummy
        steps); an empty GLOBAL cache raises.
        """
        local_max = max(
            (_entry_rows(e) for e in cache.entries), default=0
        )
        steps = agree_max(cache.num_batches, mesh)
        height = agree_max(_round_up(max(local_max, 1), row_tile), mesh)
        if steps == 0:
            raise ValueError("training stream is empty on every process")
        return SyncedReplayPlan(
            global_steps=steps, local_height=height, mesh=mesh
        )

    def epoch_batches(
        self,
        reader: Iterator[Dict[str, np.ndarray]],
        dummy: Callable[[], Any],
    ) -> Iterator[Any]:
        """Yield exactly ``global_steps`` items: the local reader's batches
        (to be padded to ``local_height`` by the caller's ``place``),
        then ``dummy()`` fillers once the local cache is drained.

        The caller's placement must pad every real batch to
        ``local_height`` rows with zero-weight padding, and ``dummy()``
        must produce a zero-weight batch of the same shape — both are
        exact no-ops in every weighted reduction, so a short process
        contributes nothing past its own data while keeping the SPMD
        step count aligned.
        """
        steps = 0
        for batch in reader:
            if steps >= self.global_steps:
                raise RuntimeError(
                    "local cache yielded more batches than the agreed "
                    "schedule — caches must be sealed before planning"
                )
            yield batch
            steps += 1
        while steps < self.global_steps:
            yield dummy()
            steps += 1


def pad_rows_to(arr: np.ndarray, height: int, dtype=None) -> np.ndarray:
    """Zero-pad ``arr`` along axis 0 to exactly ``height`` rows — the
    fixed-shape placement contract of :class:`SyncedReplayPlan` (padded
    rows must carry zero weight, making them exact no-ops). One shared
    definition so the per-trainer ``place`` functions cannot drift."""
    arr = np.asarray(arr, dtype)
    out = np.zeros((height,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


@functools.lru_cache(maxsize=64)
def _slot_gather_fn(mesh, axis: str, p_size: int, item_shape: tuple):
    """Compiled one-slot-scatter ``psum`` (== all_gather, but with
    replication the shard_map output checker can infer), cached per
    (mesh, item shape). Each device's ``[1, *item_shape]`` shard lands in
    its own slot of a ``[p_size, *item_shape]`` buffer; the psum makes
    every slot visible everywhere without cross-addition."""

    def _gather(shard):
        i = jax.lax.axis_index(axis)
        buf = jnp.zeros(
            (p_size,) + item_shape, jnp.float32
        ).at[i].set(shard[0])
        return jax.lax.psum(buf, axis)

    return jax.jit(
        jax.shard_map(_gather, mesh=mesh, in_specs=P(axis), out_specs=P(None))
    )


def gather_vectors(local_vec: np.ndarray, mesh: DeviceMesh) -> np.ndarray:
    """Gather one flat float64 vector per process; returns ``[P, len]``
    (process-indexed, every process sees all rows).

    Used to reduce host-side pass-0 statistics (GMM moments, PCA sums)
    across processes without losing f64 precision to the f32 data plane:
    each value rides as an (hi, lo) f32 pair — ``hi = f32(v)``,
    ``lo = f32(v - hi)`` — and is reassembled on the host, exact to
    ~2^-48 relative. The transport is the same one-slot-scatter ``psum``
    as :func:`pooled_sample` (no cross-process addition touches the
    split values, so reassembly is deterministic and identical on every
    host). Single-process: returns ``local_vec[None, :]``.
    """
    local_vec = np.asarray(local_vec, np.float64).ravel()
    if jax.process_count() == 1:
        return local_vec[None, :]
    dm = mesh if mesh is not None else DeviceMesh()
    axis = dm.axis_names[0]
    p_size = dm.axis_size()
    m = local_vec.shape[0]
    hi = local_vec.astype(np.float32)
    lo = (local_vec - hi.astype(np.float64)).astype(np.float32)
    pair = np.stack([hi, lo])  # [2, m]

    sharding = jax.sharding.NamedSharding(dm.mesh, P(axis))
    arr = jax.make_array_from_callback(
        (p_size, 2, m), sharding, lambda idx: pair[None]
    )
    out = _slot_gather_fn(dm.mesh, axis, p_size, (2, m))(arr)
    per_dev = np.asarray(out.addressable_shards[0].data, np.float64)
    # One representative device per process; devices group by process.
    devices = list(dm.mesh.devices.flat)
    rows, seen = [], set()
    for i, dev in enumerate(devices):
        if dev.process_index in seen:
            continue
        seen.add(dev.process_index)
        rows.append(per_dev[i, 0] + per_dev[i, 1])
    return np.stack(rows)


_EXHAUSTED, _HAVE, _ERROR = 0, 1, 2
_PAYLOAD_BASE = 1 << 22  # (code, payload) packed into one int32 agreement


def synced_stream(
    batches: Iterator[Any],
    mesh: Optional[DeviceMesh],
    check: Optional[Callable[[Any], None]] = None,
    payload: Optional[Callable[[Any], int]] = None,
) -> Iterator[Any]:
    """Iterate a ONE-SHOT local stream in SPMD lockstep, without caching.

    For single-pass trainers (PCA's mean+gram accumulation) the
    cache-first :class:`SyncedReplayPlan` would double the IO just to
    learn the step count — instead, every step all processes agree a
    small state code (exhausted / have-data / local-error) in ONE tiny
    collective:

      - any process erred → every process raises together
        (see :func:`agree_all_ok` for why rank-local raises must not
        happen);
      - any process has data → every process yields (exhausted ones get
        ``None`` — the caller dispatches a zero-weight dummy step);
      - all exhausted → iteration ends everywhere.

    ``check`` (optional) validates each local item; its failure is
    converted into the agreed error state instead of raising locally.

    ``payload`` (optional) maps each local item to a small non-negative
    int (< 2**22, e.g. the step's padded batch height); it rides the
    SAME collective packed under the state code (pmax is lexicographic
    on (code, payload)), and the generator then yields
    ``(item, agreed_payload)`` pairs — the max payload over data-bearing
    ranks — instead of bare items. Single-process: plain iteration, no
    collectives.
    """
    if jax.process_count() == 1:
        for item in batches:
            if check is not None:
                check(item)
            yield item if payload is None else (item, payload(item))
        return
    it = iter(batches)
    held_err: Optional[Exception] = None
    while True:
        # The source iterator itself can raise (e.g. an IOError reading
        # this rank's shard) — that failure is as rank-local as a failed
        # check() and must ride the same agreement, not propagate out of
        # the generator while the peers enter their next collective.
        try:
            item = next(it, None)
        except Exception as e:  # noqa: BLE001 — agreed below
            held_err = e
            item = None
        pay = 0
        if held_err is not None:
            code = _ERROR
        elif item is None:
            code = _EXHAUSTED
        else:
            code = _HAVE
            if check is not None:
                try:
                    check(item)
                except Exception as e:  # noqa: BLE001 — agreed below
                    held_err = e
                    code = _ERROR
            if code == _HAVE and payload is not None:
                pay = int(payload(item))
                if not 0 <= pay < _PAYLOAD_BASE:
                    held_err = ValueError(
                        f"synced_stream payload {pay} out of range "
                        f"[0, {_PAYLOAD_BASE})"
                    )
                    code = _ERROR
        agreed = _device_agree(code * _PAYLOAD_BASE + pay, mesh, "max")
        agreed_code, agreed_pay = divmod(agreed, _PAYLOAD_BASE)
        if agreed_code == _ERROR:
            if held_err is not None:
                raise held_err
            raise ValueError(
                "stream validation failed on another process; all ranks "
                "abort together to avoid a distributed hang"
            )
        if agreed_code == _EXHAUSTED:
            return
        # None on an exhausted rank → caller dispatches a dummy step.
        yield item if payload is None else (item, agreed_pay)


def synced_padded_stream(arrays_stream, mesh, check, row_tile, dummy_cols):
    """Lockstep-iterate a one-shot stream of variable-height items into
    fixed-shape dispatches — THE multi-process loop body shared by the
    uncached trainers (PCA's single pass, online FTRL/KMeans): yields
    ``(padded_arrays, valid_w, h)`` per agreed step, where each item is
    a tuple of arrays sharing leading height n, zero-padded to the
    agreed tile-rounded height h (h rides the :func:`synced_stream`
    payload), ``valid_w`` is 1.0 on real rows and 0.0 on padding, and a
    drained rank receives all-zero dummies shaped by ``dummy_cols``
    (the per-array trailing shapes, e.g. ``((dim,), (), ())`` for an
    (x, y, w) stream). Zero-weight rows must be exact no-ops in the
    caller's reductions."""
    def height_of(item):
        return _round_up(max(item[0].shape[0], 1), row_tile)

    for item, h in synced_stream(
        arrays_stream, mesh, check=check, payload=height_of
    ):
        if item is None:  # this rank drained; zero-weight dummy step
            item = tuple(
                np.zeros((0,) + tuple(shp), np.float32)
                for shp in dummy_cols
            )
        n = item[0].shape[0]
        padded = tuple(pad_rows_to(a, h) for a in item)
        valid_w = np.zeros(h, np.float32)
        valid_w[:n] = 1.0
        yield padded, valid_w, h


def pooled_sample(
    local_sample: np.ndarray,
    local_rows: int,
    cap: int,
    seed: int,
    mesh: DeviceMesh,
) -> np.ndarray:
    """Combine per-process uniform row samples into one global sample.

    Each process passes its local reservoir sample (``<= cap`` rows,
    uniform over its ``local_rows``-row partition). The samples are
    gathered through the device fabric (an ``all_gather`` over the data
    axis — no host side channel), then ``cap`` rows are drawn on every
    host identically (same seed ⇒ same result) by Efraimidis–Spirakis
    weighted sampling without replacement, each pooled row weighted
    ``local_rows / sample_rows`` of its home process so the draw matches
    uniform-over-the-global-dataset in expectation.

    Single-process this is the identity (the local sample IS the global
    sample). Returns ``min(cap, total pooled rows)`` rows.
    """
    local_sample = np.asarray(local_sample, np.float32)
    if jax.process_count() == 1:
        return local_sample
    if local_sample.size == 0:
        # An empty partition is legal (the process feeds only dummy
        # steps); normalize the empty reservoir's 1-D shape so the
        # feature dim comes from the agreement below.
        local_sample = local_sample.reshape(0, 0)
    if local_sample.ndim != 2:
        raise ValueError(f"sample must be [n, d], got {local_sample.shape}")
    d = agree_max(local_sample.shape[1], mesh)
    if local_sample.shape[0] and local_sample.shape[1] != d:
        raise ValueError(
            f"sample feature dim {local_sample.shape[1]} != global dim {d}"
        )
    s_p = local_sample.shape[0]
    # Gather buffers sized by the agreed ACTUAL max sample size, not the
    # nominal cap (GMM's cap is 65,536 — padding every device's slot to
    # it would burn ~cap*d*4 B per device for a few hundred real rows).
    cap_eff = max(1, agree_max(s_p, mesh))
    padded = np.zeros((cap_eff, d), np.float32)
    if s_p:
        padded[:s_p] = local_sample

    axis = mesh.axis_names[0]
    p_size = mesh.axis_size()
    # Row 0 of each device's shard block carries (sample_rows, local_rows);
    # the gathered copy is deduplicated per process on the host below.
    meta = np.array([[float(s_p), float(local_rows)]], np.float32)

    # Each device's shard is this process's whole padded sample / meta row
    # (the callback is only invoked for addressable shards).
    sharding3 = jax.sharding.NamedSharding(mesh.mesh, P(axis))
    sample_g = jax.make_array_from_callback(
        (p_size, cap_eff, d), sharding3, lambda idx: padded[None]
    )
    meta_g = jax.make_array_from_callback(
        (p_size, 2), sharding3, lambda idx: meta
    )
    gathered = _slot_gather_fn(mesh.mesh, axis, p_size, (cap_eff, d))(
        sample_g
    )
    metas = _slot_gather_fn(mesh.mesh, axis, p_size, (2,))(meta_g)
    gathered = np.asarray(gathered.addressable_shards[0].data)
    metas = np.asarray(metas.addressable_shards[0].data)

    # One representative device per process (devices of a process hold
    # identical copies; mesh device order groups by process).
    devices = list(mesh.mesh.devices.flat)
    rows, weights = [], []
    seen = set()
    for i, dev in enumerate(devices):
        p = dev.process_index
        if p in seen:
            continue
        seen.add(p)
        s_rows = int(metas[i, 0])
        n_rows = float(metas[i, 1])
        if s_rows == 0:
            continue
        rows.append(gathered[i, :s_rows])
        weights.append(np.full(s_rows, n_rows / s_rows, np.float64))
    if not rows:
        raise ValueError("pooled sample is empty on every process")
    pool = np.concatenate(rows, axis=0)
    w = np.concatenate(weights)
    take = min(cap, pool.shape[0])
    rng = np.random.default_rng(seed)
    # Efraimidis–Spirakis: top-k of u^(1/w) is a weighted sample without
    # replacement; identical seed on every host ⇒ identical selection.
    keys = rng.random(pool.shape[0]) ** (1.0 / np.maximum(w, 1e-12))
    order = np.argsort(keys)[::-1][:take]
    return pool[order]
