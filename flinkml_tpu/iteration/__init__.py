from flinkml_tpu.iteration.runtime import (
    ForwardInputsOfLastRound,
    IterationConfig,
    IterationListener,
    Iterations,
    TerminateOnMaxIter,
    TerminateOnMaxIterOrTol,
    iterate,
    notify_epoch_listeners,
)
from flinkml_tpu.iteration.device_loop import device_iterate
from flinkml_tpu.iteration.checkpoint import (
    CheckpointIntegrityError,
    CheckpointManager,
    RescaleError,
    RescalePolicy,
    reshard_rank_state,
)
from flinkml_tpu.iteration.datacache import (
    DataCache,
    DataCacheReader,
    DataCacheSnapshot,
    DataCacheWriter,
    PrefetchingDeviceFeed,
    Segment,
    cache_stream,
    replay,
)

__all__ = [
    "IterationConfig",
    "IterationListener",
    "Iterations",
    "TerminateOnMaxIter",
    "TerminateOnMaxIterOrTol",
    "iterate",
    "notify_epoch_listeners",
    "ForwardInputsOfLastRound",
    "device_iterate",
    "CheckpointIntegrityError",
    "CheckpointManager",
    "RescaleError",
    "RescalePolicy",
    "reshard_rank_state",
    "DataCache",
    "DataCacheReader",
    "DataCacheSnapshot",
    "DataCacheWriter",
    "PrefetchingDeviceFeed",
    "Segment",
    "cache_stream",
    "replay",
]
