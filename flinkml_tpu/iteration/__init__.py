from flinkml_tpu.iteration.runtime import (
    IterationConfig,
    IterationListener,
    Iterations,
    TerminateOnMaxIter,
    TerminateOnMaxIterOrTol,
    iterate,
)
from flinkml_tpu.iteration.device_loop import device_iterate
from flinkml_tpu.iteration.checkpoint import CheckpointManager

__all__ = [
    "IterationConfig",
    "IterationListener",
    "Iterations",
    "TerminateOnMaxIter",
    "TerminateOnMaxIterOrTol",
    "iterate",
    "device_iterate",
    "CheckpointManager",
]
