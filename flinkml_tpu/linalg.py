"""Vector/matrix value types and factories.

Parity with ``flink-ml-core/.../ml/linalg/``: ``DenseVector``,
``SparseVector``, ``DenseMatrix`` POJOs and the ``Vectors.dense/sparse``
factories (``Vectors.java:25,30``). The reference also ships custom Flink
serializers per type (``typeinfo/DenseVectorSerializer.java``); here
serialization is plain numpy ``.npz`` (see ``flinkml_tpu.io.read_write``) —
no custom wire format is needed because tables move as columnar batches, not
record streams.

TPU-first notes: these types are *host-side value objects* for user-facing
rows and model data. The compute path never loops over them — algorithms
convert whole columns to device arrays (``Table`` columns are already
``[rows, dim]``) and sparse data to batched CSR (``flinkml_tpu.ops.sparse``)
before touching the MXU.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Union

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two ≥ ``n`` (and ≥ 1). Shared size-quantization
    helper: padded shapes quantized to powers of two bound the number of
    distinct XLA programs to log2(max size) per call site (row buckets in
    :mod:`flinkml_tpu.pipeline_fusion`, cumsum chunk widths in
    :mod:`flinkml_tpu.ops.sparse`)."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class Vector:
    """Abstract vector. Parity: ``ml/linalg/Vector.java``."""

    def size(self) -> int:
        raise NotImplementedError

    def get(self, i: int) -> float:
        raise NotImplementedError

    def to_array(self) -> np.ndarray:
        raise NotImplementedError

    def to_dense(self) -> "DenseVector":
        return DenseVector(self.to_array())

    def __len__(self) -> int:
        return self.size()


class DenseVector(Vector):
    """Dense double vector. Parity: ``ml/linalg/DenseVector.java``."""

    def __init__(self, values: Union[np.ndarray, Sequence[float]]):
        self.values = np.asarray(values, dtype=np.float64)
        if self.values.ndim != 1:
            raise ValueError(f"DenseVector requires 1-D data, got {self.values.ndim}-D")

    def size(self) -> int:
        return self.values.shape[0]

    def get(self, i: int) -> float:
        return float(self.values[i])

    def set(self, i: int, value: float) -> None:
        self.values[i] = value

    def to_array(self) -> np.ndarray:
        return self.values

    def dot(self, other: "Vector") -> float:
        return float(np.dot(self.values, other.to_array()))

    def norm2(self) -> float:
        return float(np.linalg.norm(self.values))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, DenseVector) and np.array_equal(
            self.values, other.values
        )

    def __hash__(self) -> int:
        return hash(self.values.tobytes())

    def __repr__(self) -> str:
        return f"DenseVector({self.values.tolist()})"


class SparseVector(Vector):
    """Sorted-index sparse vector. Parity: ``ml/linalg/SparseVector.java``
    (indices kept sorted and deduplicated at construction)."""

    def __init__(
        self,
        size: int,
        indices: Union[np.ndarray, Sequence[int]],
        values: Union[np.ndarray, Sequence[float]],
    ):
        indices = np.asarray(indices, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        if indices.shape != values.shape or indices.ndim != 1:
            raise ValueError("indices and values must be 1-D with equal length")
        if indices.size > 0:
            if indices.min() < 0 or indices.max() >= size:
                raise ValueError(
                    f"index out of range for size {size}: "
                    f"[{indices.min()}, {indices.max()}]"
                )
            order = np.argsort(indices, kind="stable")
            indices = indices[order]
            values = values[order]
            if np.any(np.diff(indices) == 0):
                raise ValueError("duplicate indices in SparseVector")
        self._size = int(size)
        self.indices = indices
        self.values = values

    @classmethod
    def _from_sorted(cls, size: int, indices: np.ndarray,
                     values: np.ndarray) -> "SparseVector":
        """Internal trusted construction: skips validation and sorting.
        Callers guarantee sorted, unique, in-range int64 indices and
        float64 values — used by bulk producers (e.g. the sparse
        OneHotEncoder) where per-row validation dominates."""
        self = object.__new__(cls)
        self._size = int(size)
        self.indices = indices
        self.values = values
        return self

    def size(self) -> int:
        return self._size

    def get(self, i: int) -> float:
        if not 0 <= i < self._size:
            raise IndexError(f"index {i} out of range for size {self._size}")
        pos = np.searchsorted(self.indices, i)
        if pos < self.indices.size and self.indices[pos] == i:
            return float(self.values[pos])
        return 0.0

    def to_array(self) -> np.ndarray:
        out = np.zeros(self._size, dtype=np.float64)
        out[self.indices] = self.values
        return out

    def dot(self, other: "Vector") -> float:
        if isinstance(other, SparseVector):
            return float(np.dot(self.to_array(), other.to_array()))
        return float(np.dot(self.values, other.to_array()[self.indices]))

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, SparseVector)
            and other._size == self._size
            and np.array_equal(other.indices, self.indices)
            and np.array_equal(other.values, self.values)
        )

    def __hash__(self) -> int:
        return hash((self._size, self.indices.tobytes(), self.values.tobytes()))

    def __repr__(self) -> str:
        return (
            f"SparseVector({self._size}, {self.indices.tolist()}, "
            f"{self.values.tolist()})"
        )


class DenseMatrix:
    """Column-major dense matrix. Parity: ``ml/linalg/DenseMatrix.java``
    (the reference stores column-major for its gemv; here the backing array
    is a standard 2-D row-major numpy array — layout is XLA's concern)."""

    def __init__(self, num_rows: int, num_cols: int, values: np.ndarray = None):
        if values is None:
            values = np.zeros((num_rows, num_cols), dtype=np.float64)
        values = np.asarray(values, dtype=np.float64)
        if values.shape == (num_rows * num_cols,):
            # Accept flat column-major payloads like the reference ctor.
            values = values.reshape((num_cols, num_rows)).T.copy()
        if values.shape != (num_rows, num_cols):
            raise ValueError(
                f"values shape {values.shape} != ({num_rows}, {num_cols})"
            )
        self.values = values

    @property
    def num_rows(self) -> int:
        return self.values.shape[0]

    @property
    def num_cols(self) -> int:
        return self.values.shape[1]

    def get(self, i: int, j: int) -> float:
        return float(self.values[i, j])

    def __eq__(self, other: object) -> bool:
        return isinstance(other, DenseMatrix) and np.array_equal(
            self.values, other.values
        )

    def __repr__(self) -> str:  # pragma: no cover
        return f"DenseMatrix({self.num_rows}x{self.num_cols})"


class Vectors:
    """Factory methods. Parity: ``ml/linalg/Vectors.java:25,30``."""

    @staticmethod
    def dense(*values: float) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            return DenseVector(values[0])
        return DenseVector(list(values))

    @staticmethod
    def sparse(size: int, indices: Sequence[int], values: Sequence[float]) -> SparseVector:
        return SparseVector(size, indices, values)


def stack_vectors(vectors: Iterable[Vector]) -> np.ndarray:
    """Densify a sequence of vectors into a [rows, dim] batch array.

    The bridge from row-wise user data to the columnar compute path; sparse
    inputs at scale should use ``flinkml_tpu.ops.sparse.BatchedCSR`` instead.
    """
    rows = [v.to_array() if isinstance(v, Vector) else np.asarray(v) for v in vectors]
    return np.stack(rows).astype(np.float64)
