"""Deterministic fault injection — scripted failures for recovery proofs.

The reference *proves* its checkpoint protocol with fault-injecting
integration tests (``UnboundedStreamIterationITCase``, the
failoverCount-parameterized ``BoundedAllRoundCheckpointITCase``): a job
is killed on script, restarted, and the result compared against the
uninterrupted run. This module is that capability as a first-class
layer: a :class:`FaultPlan` of scripted faults, armed process-wide, that
fires at named **seam sites** threaded through the runtime:

========================  ====================================================
site                      where it fires
========================  ====================================================
``iteration.epoch``       top of every :func:`flinkml_tpu.iteration.iterate`
                          epoch, before that epoch's batch is consumed
``checkpoint.write``      inside ``CheckpointManager._write``, after the
                          arrays/manifest are serialized but BEFORE the
                          atomic rename (a raise here is a torn write: the
                          snapshot is never committed)
``checkpoint.committed``  right after a checkpoint's atomic rename (a raise
                          here is a kill-after-commit; the context carries
                          the committed directory so a fault can corrupt it)
``dispatch.transfer``     every ``DispatchGuard.after_dispatch`` — the
                          host↔device synchronization seam
``registry.publish``      top of ``ModelRegistry.publish``, before any file
                          is written (a raise drops the publish)
``data.read``             every source-batch read of a
                          :class:`flinkml_tpu.data.DatasetIterator`, after
                          the batch left the source and before any
                          transform touches it
``data.prefetch``         inside the :class:`flinkml_tpu.data
                          .DevicePrefetcher` worker, before each batch's
                          pad + host→device placement (a raise propagates
                          to the consumer's ``next()`` with the worker's
                          traceback; a delay models a slow producer)
``rank.lost``             top of every ``iterate`` epoch (right after
                          ``iteration.epoch``) — the elastic seam where a
                          scripted :class:`RankLost` marks a peer host
                          dead; with a watchdog in context the loss
                          becomes a clean shrink-triggering preemption
                          stop, without one it is a hard crash
``rendezvous.rescale``    inside :func:`flinkml_tpu.parallel.distributed
                          .agree_resume_epoch` — the survivors'
                          agreement on the newest commonly-valid
                          snapshot before an elastic resume (a raise
                          models a failed shrink rendezvous)
``serving.replica``       top of every :meth:`flinkml_tpu.serving
                          .ServingEngine._serve_batch` dispatch, before
                          the batch transform; the context carries the
                          engine name, so a :class:`ReplicaDown` can
                          kill ONE replica of a
                          :class:`~flinkml_tpu.serving.pool.ReplicaPool`
                          mid-traffic (every batch on that replica
                          raises from then on — the pool must retire it
                          and respread traffic; the chaos contract of
                          the ``serving scaleout`` CI stage)
========================  ====================================================

Arming is explicit and scoped (:func:`armed`); with **no plan armed the
hooks are a single module-attribute ``None`` check** at each seam —
nothing is allocated, no callable is invoked, so production paths pay
nothing. All triggers are counter/epoch based: a plan replays
identically run after run, which is what lets tests assert bit-exact
recovery (kill at epoch k, corrupt the newest snapshot, resume, compare
against the uninterrupted run — see ``tests/test_online_resume.py`` and
the chaos stage in ``tools/ci.sh``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("faults")


class FaultInjected(RuntimeError):
    """The scripted failure raised by injected faults — catch this (and
    only this) in recovery tests to distinguish the injection from a real
    bug in the code under test."""


class Fault:
    """One scripted fault. Subclasses set ``site`` and implement
    :meth:`should_fire` (pure decision — called for every event at the
    site) and :meth:`apply` (the effect: raise, delay, corrupt)."""

    site: str = ""

    def should_fire(self, ctx: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def apply(self, ctx: Dict[str, Any]) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class RaiseAtEpoch(Fault):
    """Raise :class:`FaultInjected` at the top of epoch ``epoch`` —
    the scripted mid-stream crash. The epoch's batch has NOT been
    consumed when this fires."""

    site = "iteration.epoch"

    def __init__(self, epoch: int, message: str = "injected crash"):
        self.epoch = int(epoch)
        self.message = message
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch") == self.epoch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(f"{self.message} (epoch {self.epoch})")

    def describe(self):
        return f"RaiseAtEpoch({self.epoch})"


class KillAfterCheckpoint(Fault):
    """Raise :class:`FaultInjected` immediately after the first checkpoint
    of epoch >= ``min_epoch`` commits — the snapshot IS durable, the
    process dies before training past it (the classic preemption shape)."""

    site = "checkpoint.committed"

    def __init__(self, min_epoch: int = 0):
        self.min_epoch = int(min_epoch)
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch", -1) >= self.min_epoch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected kill after checkpoint commit (epoch {ctx.get('epoch')})"
        )

    def describe(self):
        return f"KillAfterCheckpoint(min_epoch={self.min_epoch})"


class CorruptSnapshot(Fault):
    """Corrupt the just-committed snapshot (arrays bit-flip, manifest
    mangle, or truncation — see :func:`corrupt_checkpoint`) the first time
    a checkpoint of epoch >= ``min_epoch`` commits. Does not raise; pair
    it with :class:`KillAfterCheckpoint` (listed AFTER it in the plan) for
    the kill-with-corrupt-latest scenario."""

    site = "checkpoint.committed"

    def __init__(self, min_epoch: int = 0, target: str = "arrays"):
        self.min_epoch = int(min_epoch)
        self.target = target
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch", -1) >= self.min_epoch

    def apply(self, ctx):
        self.fired = True
        corrupt_checkpoint(ctx["path"], target=self.target)

    def describe(self):
        return f"CorruptSnapshot(min_epoch={self.min_epoch}, {self.target})"


class TornWrite(Fault):
    """Raise inside the checkpoint write of epoch ``epoch``, after
    serialization but before the atomic rename — the commit never
    happens, exactly like a kill mid-write. The previous snapshot must
    remain the restore point."""

    site = "checkpoint.write"

    def __init__(self, epoch: int):
        self.epoch = int(epoch)
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch") == self.epoch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected torn checkpoint write (epoch {self.epoch})"
        )

    def describe(self):
        return f"TornWrite({self.epoch})"


class TransferFault(Fault):
    """Delay (``mode='delay'``) or fail (``mode='fail'``) the N-th
    host↔device transfer seam event after arming (1-based)."""

    site = "dispatch.transfer"

    def __init__(self, at_count: int = 1, mode: str = "fail",
                 delay_s: float = 0.05):
        if mode not in ("fail", "delay"):
            raise ValueError(f"mode must be 'fail' or 'delay', got {mode!r}")
        self.at_count = int(at_count)
        self.mode = mode
        self.delay_s = float(delay_s)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_count

    def apply(self, ctx):
        self.fired = True
        if self.mode == "delay":
            time.sleep(self.delay_s)
            return
        raise FaultInjected(
            f"injected transfer failure (transfer #{self.at_count})"
        )

    def describe(self):
        return f"TransferFault(#{self.at_count}, {self.mode})"


class DropPublish(Fault):
    """Fail the N-th registry publish after arming (1-based) before any
    file is written — the publish is dropped as if the publisher crashed
    on entry; the registry is untouched."""

    site = "registry.publish"

    def __init__(self, at_publish: int = 1):
        self.at_publish = int(at_publish)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_publish

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected dropped publish (publish #{self.at_publish})"
        )

    def describe(self):
        return f"DropPublish(#{self.at_publish})"


class RaiseAtRead(Fault):
    """Raise :class:`FaultInjected` at the N-th input-pipeline read
    event after arming (1-based) — the scripted mid-stream SOURCE
    failure (a vanished file, a dead upstream). ``site`` defaults to
    ``data.read``; pass ``site='data.prefetch'`` to fail inside the
    prefetch worker instead (exercising the worker→consumer exception
    propagation path)."""

    def __init__(self, at_read: int = 1, site: str = "data.read",
                 message: str = "injected source failure"):
        if site not in ("data.read", "data.prefetch"):
            raise ValueError(
                f"site must be 'data.read' or 'data.prefetch', got {site!r}"
            )
        self.site = site
        self.at_read = int(at_read)
        self.message = message
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_read

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(f"{self.message} (read #{self.at_read})")

    def describe(self):
        return f"RaiseAtRead(#{self.at_read}, {self.site})"


class DelayRead(Fault):
    """Sleep ``delay_s`` on every input-pipeline read event (or only
    the first ``first_n``) — the deterministic slow producer, used to
    prove the prefetcher overlaps source latency with consumer compute.
    Never raises."""

    def __init__(self, delay_s: float = 0.01,
                 first_n: Optional[int] = None, site: str = "data.read"):
        if site not in ("data.read", "data.prefetch"):
            raise ValueError(
                f"site must be 'data.read' or 'data.prefetch', got {site!r}"
            )
        self.site = site
        self.delay_s = float(delay_s)
        self.first_n = None if first_n is None else int(first_n)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return self.first_n is None or self._seen <= self.first_n

    def apply(self, ctx):
        self.fired = True
        time.sleep(self.delay_s)

    def describe(self):
        n = "*" if self.first_n is None else self.first_n
        return f"DelayRead({self.delay_s}s, first_n={n}, {self.site})"


class RankLost(Fault):
    """Mark ``rank`` as LOST at the top of epoch ``epoch`` — the
    scripted host/TPU-VM loss of a preemptible fleet. When the iteration
    runs under a :class:`~flinkml_tpu.utils.preemption
    .PreemptionWatchdog`, the loss is delivered through
    ``watchdog.notify_rank_lost``: the loop stops cleanly at the epoch
    boundary, commits its final checkpoint, and the survivors plan an
    elastic resume at the shrunken world (the shrink-on-SIGTERM path).
    Without a watchdog the loss is a hard crash
    (:class:`FaultInjected`) — nobody was watching for it."""

    site = "rank.lost"

    def __init__(self, epoch: int, rank: int = 0):
        self.epoch = int(epoch)
        self.rank = int(rank)
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch") == self.epoch

    def apply(self, ctx):
        self.fired = True
        watchdog = ctx.get("watchdog")
        if watchdog is not None and hasattr(watchdog, "notify_rank_lost"):
            watchdog.notify_rank_lost(
                self.rank, reason=f"injected rank loss (epoch {self.epoch})"
            )
            return
        raise FaultInjected(
            f"injected rank loss (rank {self.rank}, epoch {self.epoch}) "
            "with no watchdog installed — hard crash"
        )

    def describe(self):
        return f"RankLost(rank={self.rank}, epoch={self.epoch})"


class ReplicaDown(Fault):
    """Kill one serving replica: from the ``at_batch``-th batch this
    replica dispatches (1-based, counted per fault instance) onward,
    EVERY batch raises :class:`FaultInjected` — the replica is dead, not
    hiccuping. ``engine`` matches the engine name (a pool replica's is
    ``"<pool>/<replica>"``, e.g. ``"pool/r1"``; a bare replica name like
    ``"r1"`` matches its suffix). The in-flight batch's requests fail
    with the injection; a :class:`~flinkml_tpu.serving.pool.ReplicaPool`
    router retries them on healthy replicas and retires the dead one."""

    site = "serving.replica"

    def __init__(self, engine: str, at_batch: int = 1):
        self.engine = str(engine)
        self.at_batch = int(at_batch)
        self._seen = 0
        self.fired = False

    def _matches(self, name: str) -> bool:
        return name == self.engine or name.endswith(f"/{self.engine}")

    def should_fire(self, ctx):
        if not self._matches(str(ctx.get("engine", ""))):
            return False
        self._seen += 1
        return self._seen >= self.at_batch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected replica death ({ctx.get('engine')}, batch "
            f"#{self._seen})"
        )

    def describe(self):
        return f"ReplicaDown({self.engine}, at_batch={self.at_batch})"


class FailRendezvous(Fault):
    """Raise :class:`FaultInjected` at the N-th ``rendezvous.rescale``
    seam event after arming (1-based) — the scripted failure of the
    survivors' elastic-resume agreement (a shrink rendezvous that never
    converges)."""

    site = "rendezvous.rescale"

    def __init__(self, at_count: int = 1):
        self.at_count = int(at_count)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_count

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected rescale-rendezvous failure (rendezvous "
            f"#{self.at_count})"
        )

    def describe(self):
        return f"FailRendezvous(#{self.at_count})"


class FaultPlan:
    """An ordered script of :class:`Fault`s. ``fire`` runs every matching
    fault in plan order (so ``[CorruptSnapshot(...), KillAfterCheckpoint
    (...)]`` corrupts the snapshot and THEN kills at the same commit).
    ``log`` records every firing — ``(site, description, ctx-summary)``
    tuples — for assertions and postmortems."""

    def __init__(self, *faults: Fault):
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self.log: List[Tuple[str, str, Dict[str, Any]]] = []

    def fire(self, site: str, **ctx: Any) -> None:
        for fault in self.faults:
            if fault.site == site and fault.should_fire(ctx):
                summary = {
                    k: v for k, v in ctx.items()
                    if isinstance(v, (int, float, str, bool))
                }
                self.log.append((site, fault.describe(), summary))
                _log.warning(
                    "fault fired at %s: %s %s", site, fault.describe(), summary
                )
                fault.apply(ctx)


# -- arming ------------------------------------------------------------------
#
# Seam hooks read this module attribute and bail on None; that read is the
# ENTIRE disarmed cost. Hooks call the module-level fire() only after the
# None check, so the armed path stays one indirection away.

ACTIVE: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide (one plan at a time; arming replaces)."""
    global ACTIVE
    ACTIVE = plan
    _log.warning("fault plan armed: %s",
                 [f.describe() for f in plan.faults])
    return plan


def disarm() -> None:
    global ACTIVE
    if ACTIVE is not None:
        _log.warning("fault plan disarmed")
    ACTIVE = None


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """``with faults.armed(FaultPlan(...)) as plan:`` — scoped arming;
    always disarms, even when the injected fault propagates."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


def fire(site: str, **ctx: Any) -> None:
    """Fire the active plan at ``site`` (no-op when disarmed). Seam code
    should guard with ``if faults.ACTIVE is not None`` first so the
    disarmed cost is one attribute read."""
    plan = ACTIVE
    if plan is not None:
        plan.fire(site, **ctx)


# -- snapshot corruption helpers --------------------------------------------
#
# Used by CorruptSnapshot and directly by tests/operators to simulate disk
# rot on committed checkpoints (layout: <dir>/ckpt-<epoch>/{arrays.npz,
# meta.json} — iteration/checkpoint.py).


def corrupt_checkpoint(ckpt_dir: str, target: str = "arrays") -> str:
    """Deterministically damage the committed checkpoint at ``ckpt_dir``:

    - ``arrays``: flip bits in the middle of ``arrays.npz`` (payload
      corruption — the manifest stays valid, only integrity verification
      can catch it);
    - ``manifest``: overwrite ``meta.json`` with non-JSON garbage;
    - ``truncate``: cut ``arrays.npz`` to half its length (torn disk
      state).

    Returns the path it damaged.
    """
    if target == "manifest":
        path = os.path.join(ckpt_dir, "meta.json")
        with open(path, "w") as f:
            f.write('{"epoch": CORRUPTED')
        _log.warning("corrupted checkpoint manifest: %s", path)
        return path
    path = os.path.join(ckpt_dir, "arrays.npz")
    size = os.path.getsize(path)
    if target == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
        _log.warning("truncated checkpoint arrays: %s", path)
        return path
    if target != "arrays":
        raise ValueError(
            f"target must be 'arrays', 'manifest' or 'truncate', got {target!r}"
        )
    with open(path, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(16)
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    _log.warning("corrupted checkpoint arrays: %s", path)
    return path


def corrupt_latest(manager: Any, target: str = "arrays") -> int:
    """Damage the newest committed checkpoint of ``manager`` (a
    :class:`~flinkml_tpu.iteration.CheckpointManager`); returns the epoch
    it damaged. Raises when the manager holds no checkpoints."""
    epoch = manager.latest_epoch()
    if epoch is None:
        raise ValueError(f"no checkpoints under {manager.directory}")
    corrupt_checkpoint(
        os.path.join(manager.directory, f"ckpt-{epoch}"), target=target
    )
    return epoch
