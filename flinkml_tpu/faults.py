"""Deterministic fault injection — scripted failures for recovery proofs.

The reference *proves* its checkpoint protocol with fault-injecting
integration tests (``UnboundedStreamIterationITCase``, the
failoverCount-parameterized ``BoundedAllRoundCheckpointITCase``): a job
is killed on script, restarted, and the result compared against the
uninterrupted run. This module is that capability as a first-class
layer: a :class:`FaultPlan` of scripted faults, armed process-wide, that
fires at named **seam sites** threaded through the runtime:

========================  ====================================================
site                      where it fires
========================  ====================================================
``iteration.epoch``       top of every :func:`flinkml_tpu.iteration.iterate`
                          epoch, before that epoch's batch is consumed
``checkpoint.write``      inside ``CheckpointManager._write``, after the
                          arrays/manifest are serialized but BEFORE the
                          atomic rename (a raise here is a torn write: the
                          snapshot is never committed)
``checkpoint.committed``  right after a checkpoint's atomic rename (a raise
                          here is a kill-after-commit; the context carries
                          the committed directory so a fault can corrupt it)
``dispatch.transfer``     every ``DispatchGuard.after_dispatch`` — the
                          host↔device synchronization seam
``registry.publish``      top of ``ModelRegistry.publish``, before any file
                          is written (a raise drops the publish)
``data.read``             every source-batch read of a
                          :class:`flinkml_tpu.data.DatasetIterator`, after
                          the batch left the source and before any
                          transform touches it
``data.prefetch``         inside the :class:`flinkml_tpu.data
                          .DevicePrefetcher` worker, before each batch's
                          pad + host→device placement (a raise propagates
                          to the consumer's ``next()`` with the worker's
                          traceback; a delay models a slow producer)
``rank.lost``             top of every ``iterate`` epoch (right after
                          ``iteration.epoch``) — the elastic seam where a
                          scripted :class:`RankLost` marks a peer host
                          dead; with a watchdog in context the loss
                          becomes a clean shrink-triggering preemption
                          stop, without one it is a hard crash
``rendezvous.rescale``    inside :func:`flinkml_tpu.parallel.distributed
                          .agree_resume_epoch` — the survivors'
                          agreement on the newest commonly-valid
                          snapshot before an elastic resume (a raise
                          models a failed shrink rendezvous)
``serving.replica``       top of every :meth:`flinkml_tpu.serving
                          .ServingEngine._serve_batch` dispatch, before
                          the batch transform; the context carries the
                          engine name, so a :class:`ReplicaDown` can
                          kill ONE replica of a
                          :class:`~flinkml_tpu.serving.pool.ReplicaPool`
                          mid-traffic (every batch on that replica
                          raises from then on — the pool must retire it
                          and respread traffic; the chaos contract of
                          the ``serving scaleout`` CI stage)
``cluster.worker``        inside a :mod:`flinkml_tpu.cluster` worker
                          process: before every predict dispatch of the
                          worker harness (context: ``worker``,
                          ``request``), and — via the fuzz soak's
                          seam-firing feed — at every trainer batch
                          edge (context: ``epoch``). A scripted
                          :class:`WorkerCrash` hard-exits the PROCESS
                          (``os._exit``), so the failure crosses a real
                          process boundary: the serving pool must see
                          ``WorkerDiedError`` and fail over; the fuzz
                          orchestrator must restart the trainer child
                          and prove resume (no silent fresh start,
                          ledger parity) across the kill
``train.step``            around every training step of
                          :func:`flinkml_tpu.iteration.iterate` and
                          ``sharding.apply.train_linear_plan`` — fired
                          twice per step with ``phase='pre'`` (the
                          context carries the ``batch``: a
                          :class:`PoisonBatch` replaces it with a
                          NaN-filled twin) and ``phase='post'`` (the
                          context carries the post-step ``state`` and
                          ``criteria``: :class:`NaNGrad` poisons the
                          float state leaves, :class:`InfLoss` the
                          loss). These faults mutate the fired context
                          instead of raising — the numerics-sentinel
                          seam (``flinkml_tpu.recovery``), not a crash
                          seam; they re-fire on every visit to their
                          batch, so only quarantining the batch heals
                          the run (a deterministically poisoned batch,
                          not a transient flake)
========================  ====================================================

Arming is explicit and scoped (:func:`armed`); with **no plan armed the
hooks are a single module-attribute ``None`` check** at each seam —
nothing is allocated, no callable is invoked, so production paths pay
nothing. All triggers are counter/epoch based: a plan replays
identically run after run, which is what lets tests assert bit-exact
recovery (kill at epoch k, corrupt the newest snapshot, resume, compare
against the uninterrupted run — see ``tests/test_online_resume.py`` and
the chaos stage in ``tools/ci.sh``).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("faults")


class FaultInjected(RuntimeError):
    """The scripted failure raised by injected faults — catch this (and
    only this) in recovery tests to distinguish the injection from a real
    bug in the code under test."""


class Fault:
    """One scripted fault. Subclasses set ``site`` and implement
    :meth:`should_fire` (pure decision — called for every event at the
    site) and :meth:`apply` (the effect: raise, delay, corrupt)."""

    site: str = ""

    def should_fire(self, ctx: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def apply(self, ctx: Dict[str, Any]) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class RaiseAtEpoch(Fault):
    """Raise :class:`FaultInjected` at the top of epoch ``epoch`` —
    the scripted mid-stream crash. The epoch's batch has NOT been
    consumed when this fires."""

    site = "iteration.epoch"

    def __init__(self, epoch: int, message: str = "injected crash"):
        self.epoch = int(epoch)
        self.message = message
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch") == self.epoch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(f"{self.message} (epoch {self.epoch})")

    def describe(self):
        return f"RaiseAtEpoch({self.epoch})"


class KillAfterCheckpoint(Fault):
    """Raise :class:`FaultInjected` immediately after the first checkpoint
    of epoch >= ``min_epoch`` commits — the snapshot IS durable, the
    process dies before training past it (the classic preemption shape)."""

    site = "checkpoint.committed"

    def __init__(self, min_epoch: int = 0):
        self.min_epoch = int(min_epoch)
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch", -1) >= self.min_epoch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected kill after checkpoint commit (epoch {ctx.get('epoch')})"
        )

    def describe(self):
        return f"KillAfterCheckpoint(min_epoch={self.min_epoch})"


class CorruptSnapshot(Fault):
    """Corrupt the just-committed snapshot (arrays bit-flip, manifest
    mangle, or truncation — see :func:`corrupt_checkpoint`) the first time
    a checkpoint of epoch >= ``min_epoch`` commits. Does not raise; pair
    it with :class:`KillAfterCheckpoint` (listed AFTER it in the plan) for
    the kill-with-corrupt-latest scenario."""

    site = "checkpoint.committed"

    def __init__(self, min_epoch: int = 0, target: str = "arrays"):
        self.min_epoch = int(min_epoch)
        self.target = target
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch", -1) >= self.min_epoch

    def apply(self, ctx):
        self.fired = True
        corrupt_checkpoint(ctx["path"], target=self.target)

    def describe(self):
        return f"CorruptSnapshot(min_epoch={self.min_epoch}, {self.target})"


class TornWrite(Fault):
    """Raise inside the checkpoint write of epoch ``epoch``, after
    serialization but before the atomic rename — the commit never
    happens, exactly like a kill mid-write. The previous snapshot must
    remain the restore point."""

    site = "checkpoint.write"

    def __init__(self, epoch: int):
        self.epoch = int(epoch)
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch") == self.epoch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected torn checkpoint write (epoch {self.epoch})"
        )

    def describe(self):
        return f"TornWrite({self.epoch})"


class TransferFault(Fault):
    """Delay (``mode='delay'``) or fail (``mode='fail'``) the N-th
    host↔device transfer seam event after arming (1-based)."""

    site = "dispatch.transfer"

    def __init__(self, at_count: int = 1, mode: str = "fail",
                 delay_s: float = 0.05):
        if mode not in ("fail", "delay"):
            raise ValueError(f"mode must be 'fail' or 'delay', got {mode!r}")
        self.at_count = int(at_count)
        self.mode = mode
        self.delay_s = float(delay_s)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_count

    def apply(self, ctx):
        self.fired = True
        if self.mode == "delay":
            time.sleep(self.delay_s)
            return
        raise FaultInjected(
            f"injected transfer failure (transfer #{self.at_count})"
        )

    def describe(self):
        return f"TransferFault(#{self.at_count}, {self.mode})"


class DropPublish(Fault):
    """Fail the N-th registry publish after arming (1-based) before any
    file is written — the publish is dropped as if the publisher crashed
    on entry; the registry is untouched."""

    site = "registry.publish"

    def __init__(self, at_publish: int = 1):
        self.at_publish = int(at_publish)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_publish

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected dropped publish (publish #{self.at_publish})"
        )

    def describe(self):
        return f"DropPublish(#{self.at_publish})"


class RaiseAtRead(Fault):
    """Raise :class:`FaultInjected` at the N-th input-pipeline read
    event after arming (1-based) — the scripted mid-stream SOURCE
    failure (a vanished file, a dead upstream). ``site`` defaults to
    ``data.read``; pass ``site='data.prefetch'`` to fail inside the
    prefetch worker instead (exercising the worker→consumer exception
    propagation path)."""

    def __init__(self, at_read: int = 1, site: str = "data.read",
                 message: str = "injected source failure"):
        if site not in ("data.read", "data.prefetch"):
            raise ValueError(
                f"site must be 'data.read' or 'data.prefetch', got {site!r}"
            )
        self.site = site
        self.at_read = int(at_read)
        self.message = message
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_read

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(f"{self.message} (read #{self.at_read})")

    def describe(self):
        return f"RaiseAtRead(#{self.at_read}, {self.site})"


class DelayRead(Fault):
    """Sleep ``delay_s`` on every input-pipeline read event (or only
    the first ``first_n``) — the deterministic slow producer, used to
    prove the prefetcher overlaps source latency with consumer compute.
    Never raises."""

    def __init__(self, delay_s: float = 0.01,
                 first_n: Optional[int] = None, site: str = "data.read"):
        if site not in ("data.read", "data.prefetch"):
            raise ValueError(
                f"site must be 'data.read' or 'data.prefetch', got {site!r}"
            )
        self.site = site
        self.delay_s = float(delay_s)
        self.first_n = None if first_n is None else int(first_n)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return self.first_n is None or self._seen <= self.first_n

    def apply(self, ctx):
        self.fired = True
        time.sleep(self.delay_s)

    def describe(self):
        n = "*" if self.first_n is None else self.first_n
        return f"DelayRead({self.delay_s}s, first_n={n}, {self.site})"


class RankLost(Fault):
    """Mark ``rank`` as LOST at the top of epoch ``epoch`` — the
    scripted host/TPU-VM loss of a preemptible fleet. When the iteration
    runs under a :class:`~flinkml_tpu.utils.preemption
    .PreemptionWatchdog`, the loss is delivered through
    ``watchdog.notify_rank_lost``: the loop stops cleanly at the epoch
    boundary, commits its final checkpoint, and the survivors plan an
    elastic resume at the shrunken world (the shrink-on-SIGTERM path).
    Without a watchdog the loss is a hard crash
    (:class:`FaultInjected`) — nobody was watching for it."""

    site = "rank.lost"

    def __init__(self, epoch: int, rank: int = 0):
        self.epoch = int(epoch)
        self.rank = int(rank)
        self.fired = False

    def should_fire(self, ctx):
        return not self.fired and ctx.get("epoch") == self.epoch

    def apply(self, ctx):
        self.fired = True
        watchdog = ctx.get("watchdog")
        if watchdog is not None and hasattr(watchdog, "notify_rank_lost"):
            watchdog.notify_rank_lost(
                self.rank, reason=f"injected rank loss (epoch {self.epoch})"
            )
            return
        raise FaultInjected(
            f"injected rank loss (rank {self.rank}, epoch {self.epoch}) "
            "with no watchdog installed — hard crash"
        )

    def describe(self):
        return f"RankLost(rank={self.rank}, epoch={self.epoch})"


class ReplicaDown(Fault):
    """Kill one serving replica: from the ``at_batch``-th batch this
    replica dispatches (1-based, counted per fault instance) onward,
    EVERY batch raises :class:`FaultInjected` — the replica is dead, not
    hiccuping. ``engine`` matches the engine name (a pool replica's is
    ``"<pool>/<replica>"``, e.g. ``"pool/r1"``; a bare replica name like
    ``"r1"`` matches its suffix). The in-flight batch's requests fail
    with the injection; a :class:`~flinkml_tpu.serving.pool.ReplicaPool`
    router retries them on healthy replicas and retires the dead one."""

    site = "serving.replica"

    def __init__(self, engine: str, at_batch: int = 1):
        self.engine = str(engine)
        self.at_batch = int(at_batch)
        self._seen = 0
        self.fired = False

    def _matches(self, name: str) -> bool:
        return name == self.engine or name.endswith(f"/{self.engine}")

    def should_fire(self, ctx):
        if not self._matches(str(ctx.get("engine", ""))):
            return False
        self._seen += 1
        return self._seen >= self.at_batch

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected replica death ({ctx.get('engine')}, batch "
            f"#{self._seen})"
        )

    def describe(self):
        return f"ReplicaDown({self.engine}, at_batch={self.at_batch})"


class StallDispatch(Fault):
    """The GRAY failure: the replica is alive but frozen. From the
    ``at_batch``-th batch this replica dispatches (1-based, per fault
    instance) onward, every batch SLEEPS ``delay_s`` before serving
    normally — no error is ever raised, so nothing binary (error
    thresholds, retirement) can see it; only latency can. With
    ``for_batches=None`` the stall never clears; a finite value stalls
    exactly that many batches and then recovers — the
    quarantine→canary→rejoin lifecycle's test fixture. ``engine``
    matches like :class:`ReplicaDown` (exact name or ``/<engine>``
    suffix)."""

    site = "serving.replica"

    def __init__(self, engine: str, at_batch: int = 1,
                 delay_s: float = 0.25,
                 for_batches: Optional[int] = None):
        self.engine = str(engine)
        self.at_batch = int(at_batch)
        self.delay_s = float(delay_s)
        self.for_batches = None if for_batches is None else int(for_batches)
        self._seen = 0
        self._stalled = 0
        self.fired = False

    def _matches(self, name: str) -> bool:
        return name == self.engine or name.endswith(f"/{self.engine}")

    def should_fire(self, ctx):
        if not self._matches(str(ctx.get("engine", ""))):
            return False
        self._seen += 1
        if self._seen < self.at_batch:
            return False
        if self.for_batches is not None and self._stalled >= self.for_batches:
            return False  # the stall cleared: back to normal service
        return True

    def apply(self, ctx):
        self.fired = True
        self._stalled += 1
        time.sleep(self.delay_s)

    def describe(self):
        span = ("forever" if self.for_batches is None
                else f"for {self.for_batches} batches")
        return (f"StallDispatch({self.engine}, at_batch={self.at_batch}, "
                f"delay_s={self.delay_s}, {span})")


class JitterDispatch(Fault):
    """Intermittent slowness: each batch this replica dispatches sleeps
    ``delay_s`` with probability ``p`` — the flapping gray failure that
    a naive one-strike quarantine would thrash on. Deterministic: the
    draw sequence derives from ``seed`` alone, so a JSON-committed repro
    (:func:`fault_to_spec`) replays the exact same stall pattern."""

    site = "serving.replica"

    def __init__(self, engine: str, p: float = 0.2, delay_s: float = 0.1,
                 seed: int = 0):
        self.engine = str(engine)
        self.p = float(p)
        self.delay_s = float(delay_s)
        self.seed = int(seed)
        import numpy as np

        self._rng = np.random.default_rng(self.seed)
        self.fired = False

    def _matches(self, name: str) -> bool:
        return name == self.engine or name.endswith(f"/{self.engine}")

    def should_fire(self, ctx):
        if not self._matches(str(ctx.get("engine", ""))):
            return False
        return bool(self._rng.random() < self.p)

    def apply(self, ctx):
        self.fired = True
        time.sleep(self.delay_s)

    def describe(self):
        return (f"JitterDispatch({self.engine}, p={self.p}, "
                f"delay_s={self.delay_s}, seed={self.seed})")


class SlowRamp(Fault):
    """Gradual degradation: from ``at_batch`` onward each batch this
    replica dispatches sleeps ``step_s`` MORE than the one before,
    capped at ``max_s`` — the leaking-resource / thermal-throttle shape,
    which defeats any fixed-threshold detector that only compares
    against its own recent past (the MAD test compares against
    SIBLINGS, so it still trips)."""

    site = "serving.replica"

    def __init__(self, engine: str, at_batch: int = 1,
                 step_s: float = 0.02, max_s: float = 0.5):
        self.engine = str(engine)
        self.at_batch = int(at_batch)
        self.step_s = float(step_s)
        self.max_s = float(max_s)
        self._seen = 0
        self.fired = False

    def _matches(self, name: str) -> bool:
        return name == self.engine or name.endswith(f"/{self.engine}")

    def should_fire(self, ctx):
        if not self._matches(str(ctx.get("engine", ""))):
            return False
        self._seen += 1
        return self._seen >= self.at_batch

    def apply(self, ctx):
        self.fired = True
        ramp = (self._seen - self.at_batch + 1) * self.step_s
        time.sleep(min(ramp, self.max_s))

    def describe(self):
        return (f"SlowRamp({self.engine}, at_batch={self.at_batch}, "
                f"step_s={self.step_s}, max_s={self.max_s})")


class WorkerCrash(Fault):
    """Hard-exit the PROCESS at a ``cluster.worker`` seam event — the
    real process death behind the chaos stages' "kill a worker
    mid-traffic" and the fuzz soak's orchestrator-restart-across-a-
    process-boundary invariants. Fires when the context value under
    ``key`` (``"request"`` for the serving worker's predict counter,
    ``"epoch"`` for the trainer feed's batch edge) reaches ``at``;
    ``apply`` calls ``os._exit(exit_code)`` — no cleanup, no excuses,
    exactly like an OOM kill or a preemption.

    Cross-RESTART once-semantics need state that survives the process:
    an in-memory ``fired`` flag dies with the worker, and a restarted
    child re-arming the same plan would crash at the same trigger
    forever. ``marker`` (a file path, JSON-serializable with the plan)
    is that state: the fault touches it just before exiting and never
    fires while it exists."""

    site = "cluster.worker"

    def __init__(self, at: int = 1, key: str = "request",
                 exit_code: int = 23, marker: Optional[str] = None):
        self.at = int(at)
        self.key = str(key)
        self.exit_code = int(exit_code)
        self.marker = marker
        self.fired = False

    def should_fire(self, ctx):
        value = ctx.get(self.key)
        if value is None or int(value) < self.at:
            return False
        if self.marker is not None and os.path.exists(self.marker):
            return False
        return not self.fired

    def apply(self, ctx):
        self.fired = True
        _log.warning(
            "injected worker crash (%s=%s >= %d), exiting %d",
            self.key, ctx.get(self.key), self.at, self.exit_code,
        )
        if self.marker is not None:
            with open(self.marker, "w") as f:
                f.write(f"{self.key}={ctx.get(self.key)}\n")
                f.flush()
                os.fsync(f.fileno())
        os._exit(self.exit_code)

    def describe(self):
        return (f"WorkerCrash({self.key}>={self.at}, "
                f"exit={self.exit_code})")


class FailRendezvous(Fault):
    """Raise :class:`FaultInjected` at the N-th ``rendezvous.rescale``
    seam event after arming (1-based) — the scripted failure of the
    survivors' elastic-resume agreement (a shrink rendezvous that never
    converges)."""

    site = "rendezvous.rescale"

    def __init__(self, at_count: int = 1):
        self.at_count = int(at_count)
        self._seen = 0
        self.fired = False

    def should_fire(self, ctx):
        self._seen += 1
        return not self.fired and self._seen == self.at_count

    def apply(self, ctx):
        self.fired = True
        raise FaultInjected(
            f"injected rescale-rendezvous failure (rendezvous "
            f"#{self.at_count})"
        )

    def describe(self):
        return f"FailRendezvous(#{self.at_count})"


# -- train.step numerics faults ----------------------------------------------
#
# These do NOT raise: they corrupt the fired context in place (the seam
# code reads the possibly-replaced values back out), modeling silent
# numerics damage — a poisoned input batch, a NaN'd gradient, an
# overflowed loss — that only a numerics sentinel
# (flinkml_tpu.recovery) can catch. They key on the SOURCE batch index
# (``source_index`` in the context: the position in the un-quarantined
# feed, equal to the epoch until a batch is quarantined) and re-fire on
# EVERY visit: rolling back and retrying the same batch fails the same
# way, so the only recovery that converges is quarantining the batch —
# which is exactly the contract the recovery engine implements.


def _poison_float_leaves(tree):
    """NaN-fill every floating leaf of a pytree (int/bool leaves — model
    versions, counters — pass through untouched). Multiplying by NaN
    preserves device placement/sharding of jax arrays."""
    import jax
    import numpy as np

    def one(leaf):
        if hasattr(leaf, "dtype") and np.issubdtype(
                np.dtype(leaf.dtype), np.floating):
            return leaf * float("nan")
        return leaf

    return jax.tree_util.tree_map(one, tree)


def _poison_batch_value(batch):
    """A NaN-filled twin of a training batch: every float column/array
    becomes all-NaN, non-float data and the container shape survive
    (so shapes/buckets — and therefore compile caches — are
    untouched)."""
    import numpy as np

    try:
        from flinkml_tpu.table import Table
    except ImportError:  # pragma: no cover
        Table = None
    if Table is not None and isinstance(batch, Table):
        cols = {}
        for name in batch.column_names:
            arr = np.asarray(batch.column(name))
            if np.issubdtype(arr.dtype, np.floating):
                arr = np.full_like(arr, np.nan)
            cols[name] = arr
        return Table(cols)
    if isinstance(batch, dict):
        return {k: _poison_batch_value(v) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        out = [_poison_batch_value(v) for v in batch]
        return tuple(out) if isinstance(batch, tuple) else out
    if hasattr(batch, "dtype"):
        return _poison_float_leaves(batch)
    return batch


class NaNGrad(Fault):
    """Poison the post-step state at source batch ``at_epoch`` — the
    scripted NaN gradient: every float leaf of the step's output state
    becomes NaN, exactly as a NaN'd gradient propagated into the
    parameters would leave it. Re-fires on every retry of that batch
    (see the train.step notes above)."""

    site = "train.step"

    def __init__(self, at_epoch: int):
        self.at_epoch = int(at_epoch)
        self.fired = False

    def should_fire(self, ctx):
        return (ctx.get("phase") == "post"
                and ctx.get("source_index") == self.at_epoch)

    def apply(self, ctx):
        self.fired = True
        ctx["state"] = _poison_float_leaves(ctx["state"])

    def describe(self):
        return f"NaNGrad(at_epoch={self.at_epoch})"


class InfLoss(Fault):
    """Overflow the step's loss to +inf at source batch ``at_epoch``
    (the state stays finite — the overflowed-loss shape a too-hot batch
    produces). Re-fires on every retry of that batch."""

    site = "train.step"

    def __init__(self, at_epoch: int):
        self.at_epoch = int(at_epoch)
        self.fired = False

    def should_fire(self, ctx):
        return (ctx.get("phase") == "post"
                and ctx.get("source_index") == self.at_epoch)

    def apply(self, ctx):
        self.fired = True
        ctx["criteria"] = float("inf")

    def describe(self):
        return f"InfLoss(at_epoch={self.at_epoch})"


class PoisonBatch(Fault):
    """Replace source batch ``at_batch``'s float data with NaN before
    the step consumes it — the scripted poisoned input (a corrupted
    upstream record, a bad feature join). Re-fires on every retry: the
    batch itself is bad, and only quarantining it heals the run."""

    site = "train.step"

    def __init__(self, at_batch: int):
        self.at_batch = int(at_batch)
        self.fired = False

    def should_fire(self, ctx):
        return (ctx.get("phase") == "pre"
                and ctx.get("source_index") == self.at_batch)

    def apply(self, ctx):
        self.fired = True
        ctx["batch"] = _poison_batch_value(ctx["batch"])

    def describe(self):
        return f"PoisonBatch(at_batch={self.at_batch})"


class FaultPlan:
    """An ordered script of :class:`Fault`s. ``fire`` runs every matching
    fault in plan order (so ``[CorruptSnapshot(...), KillAfterCheckpoint
    (...)]`` corrupts the snapshot and THEN kills at the same commit).
    ``log`` records every firing — ``(site, description, ctx-summary)``
    tuples — for assertions and postmortems."""

    def __init__(self, *faults: Fault):
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self.log: List[Tuple[str, str, Dict[str, Any]]] = []

    def fire(self, site: str, **ctx: Any) -> None:
        self.fire_into(site, ctx)

    def fire_into(self, site: str, ctx: Dict[str, Any]) -> None:
        """Like :meth:`fire` but over a caller-owned context dict, so
        mutating faults (the ``train.step`` family) can hand replaced
        values — a poisoned batch, a NaN'd state — back to the seam."""
        for fault in self.faults:
            if fault.site == site and fault.should_fire(ctx):
                summary = {
                    k: v for k, v in ctx.items()
                    if isinstance(v, (int, float, str, bool))
                }
                self.log.append((site, fault.describe(), summary))
                _log.warning(
                    "fault fired at %s: %s %s", site, fault.describe(), summary
                )
                fault.apply(ctx)


# -- arming ------------------------------------------------------------------
#
# Seam hooks read this module attribute and bail on None; that read is the
# ENTIRE disarmed cost. Hooks call the module-level fire() only after the
# None check, so the armed path stays one indirection away.

ACTIVE: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide (one plan at a time; arming replaces)."""
    global ACTIVE
    ACTIVE = plan
    _log.warning("fault plan armed: %s",
                 [f.describe() for f in plan.faults])
    return plan


def disarm() -> None:
    global ACTIVE
    if ACTIVE is not None:
        _log.warning("fault plan disarmed")
    ACTIVE = None


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """``with faults.armed(FaultPlan(...)) as plan:`` — scoped arming;
    always disarms, even when the injected fault propagates."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


def fire(site: str, **ctx: Any) -> None:
    """Fire the active plan at ``site`` (no-op when disarmed). Seam code
    should guard with ``if faults.ACTIVE is not None`` first so the
    disarmed cost is one attribute read."""
    plan = ACTIVE
    if plan is not None:
        plan.fire(site, **ctx)


def fire_into(site: str, ctx: Dict[str, Any]) -> None:
    """Mutable-context variant of :func:`fire` for seams whose faults
    replace values (``train.step``): the seam reads the possibly-mutated
    entries back out of ``ctx`` after the call. Same disarmed-cost
    contract (guard with ``faults.ACTIVE is not None`` first)."""
    plan = ACTIVE
    if plan is not None:
        plan.fire_into(site, ctx)


# -- snapshot corruption helpers --------------------------------------------
#
# Used by CorruptSnapshot and directly by tests/operators to simulate disk
# rot on committed checkpoints (layout: <dir>/ckpt-<epoch>/{arrays.npz,
# meta.json} — iteration/checkpoint.py).


def corrupt_checkpoint(ckpt_dir: str, target: str = "arrays") -> str:
    """Deterministically damage the committed checkpoint at ``ckpt_dir``:

    - ``arrays``: flip bits in the middle of ``arrays.npz`` (payload
      corruption — the manifest stays valid, only integrity verification
      can catch it);
    - ``manifest``: overwrite ``meta.json`` with non-JSON garbage;
    - ``truncate``: cut ``arrays.npz`` to half its length (torn disk
      state).

    Returns the path it damaged.
    """
    if target == "manifest":
        path = os.path.join(ckpt_dir, "meta.json")
        with open(path, "w") as f:
            f.write('{"epoch": CORRUPTED')
        _log.warning("corrupted checkpoint manifest: %s", path)
        return path
    path = os.path.join(ckpt_dir, "arrays.npz")
    size = os.path.getsize(path)
    if target == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
        _log.warning("truncated checkpoint arrays: %s", path)
        return path
    if target != "arrays":
        raise ValueError(
            f"target must be 'arrays', 'manifest' or 'truncate', got {target!r}"
        )
    with open(path, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(16)
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    _log.warning("corrupted checkpoint arrays: %s", path)
    return path


def corrupt_latest(manager: Any, target: str = "arrays") -> int:
    """Damage the newest committed checkpoint of ``manager`` (a
    :class:`~flinkml_tpu.iteration.CheckpointManager`); returns the epoch
    it damaged. Raises when the manager holds no checkpoints."""
    epoch = manager.latest_epoch()
    if epoch is None:
        raise ValueError(f"no checkpoints under {manager.directory}")
    corrupt_checkpoint(
        os.path.join(manager.directory, f"ckpt-{epoch}"), target=target
    )
    return epoch


# -- plan serialization (deterministic repro artifacts) ----------------------
#
# A FaultPlan round-trips through JSON so the chaos soak
# (flinkml_tpu.recovery.fuzz) can COMMIT a failing schedule as a minimal
# reproducer: deserializing builds fresh fault instances (fired flags
# and counters reset), so a written repro replays the exact schedule.
# Specs are derived from each fault class's __init__ signature — every
# fault stores its constructor args under the same attribute names.


def fault_types() -> Dict[str, type]:
    """Every concrete :class:`Fault` subclass in this module, by name."""
    return {
        cls.__name__: cls
        for cls in globals().values()
        if isinstance(cls, type) and issubclass(cls, Fault)
        and cls is not Fault
    }


def fault_to_spec(fault: Fault) -> Dict[str, Any]:
    """``{"type": <class>, <arg>: <value>, ...}`` — the JSON-safe
    constructor record of one fault."""
    import inspect

    spec: Dict[str, Any] = {"type": type(fault).__name__}
    sig = inspect.signature(type(fault).__init__)
    for name in sig.parameters:
        if name == "self":
            continue
        if not hasattr(fault, name):
            raise ValueError(
                f"{type(fault).__name__} does not store constructor arg "
                f"{name!r}; cannot serialize"
            )
        spec[name] = getattr(fault, name)
    return spec


def fault_from_spec(spec: Dict[str, Any]) -> Fault:
    """Rebuild a fresh fault instance from :func:`fault_to_spec`'s
    record (unknown types raise ``ValueError``)."""
    kwargs = dict(spec)
    name = kwargs.pop("type", None)
    types = fault_types()
    if name not in types:
        raise ValueError(f"unknown fault type {name!r} "
                         f"(known: {sorted(types)})")
    return types[name](**kwargs)


def plan_to_json(plan: FaultPlan, extra: Optional[Dict[str, Any]] = None
                 ) -> str:
    """Serialize ``plan`` (plan order preserved) plus optional metadata
    — the committed-repro format of the chaos soak."""
    import json

    record = dict(extra or {})
    record["faults"] = [fault_to_spec(f) for f in plan.faults]
    return json.dumps(record, indent=2, sort_keys=True)


def plan_from_json(payload: str) -> FaultPlan:
    """Rebuild a fresh :class:`FaultPlan` from :func:`plan_to_json`
    output (fired flags reset — the plan replays from scratch)."""
    import json

    record = json.loads(payload)
    return FaultPlan(*[fault_from_spec(s) for s in record["faults"]])


# -- randomized schedule sampling (the chaos-soak front end) -----------------


class FuzzPlan:
    """Deterministic sampler of fault schedules for the chaos soak
    (:mod:`flinkml_tpu.recovery.fuzz`).

    ``sample(i)`` derives schedule ``i`` purely from ``(seed, i)``: the
    same (seed, index) always yields the same :class:`FaultPlan`, so a
    soak failure is reproducible by index alone (and shrinkable to a
    committed minimal repro — :func:`plan_to_json`). Each schedule draws
    1–``max_faults`` faults from the catalog entries whose seam site is
    in ``seams``, with epoch/batch triggers inside ``horizon`` (the
    scenario's batch count).

    Args:
        seed: the soak's RNG seed.
        seams: seam sites to sample across (default: the trainer-loop
            seams a device-free online fit exercises — iteration.epoch,
            rank.lost, checkpoint.write, checkpoint.committed,
            data.read, and the train.step numerics faults).
        budget: how many schedules a full soak runs (``schedules()``
            yields exactly this many).
        horizon: the scenario's batch/epoch count — triggers are
            sampled in ``[1, horizon - 1]``.
        max_faults: most faults per schedule.
        replicas: size of the serving pool the ``serving.replica``
            sampler targets — drawn engine names are ``r0..r{n-1}``
            (matched by suffix against the pool's ``<pool>/rK`` engine
            names). Ignored unless that seam is in ``seams``.
        marker_dir: directory for :class:`WorkerCrash` once-markers
            (the ``cluster.worker`` sampler needs crash-once-across-
            restarts semantics; each drawn crash gets its own marker
            file under this directory). Required when that seam is in
            ``seams``.
    """

    DEFAULT_SEAMS = (
        "iteration.epoch",
        "rank.lost",
        "checkpoint.write",
        "checkpoint.committed",
        "data.read",
        "train.step",
    )

    def __init__(self, seed: int, seams: Optional[Tuple[str, ...]] = None,
                 budget: int = 25, horizon: int = 10, max_faults: int = 3,
                 replicas: int = 4, marker_dir: Optional[str] = None):
        self.seed = int(seed)
        self.seams = tuple(seams) if seams is not None else self.DEFAULT_SEAMS
        self.budget = int(budget)
        self.horizon = int(horizon)
        self.max_faults = int(max_faults)
        self.replicas = int(replicas)
        self.marker_dir = marker_dir
        if "cluster.worker" in self.seams and not marker_dir:
            raise ValueError(
                "the cluster.worker seam samples WorkerCrash faults, "
                "which need marker_dir for crash-once-across-restarts "
                "semantics"
            )
        if self.horizon < 3:
            raise ValueError(f"horizon must be >= 3, got {self.horizon}")
        unknown = set(self.seams) - set(self._samplers())
        if unknown:
            raise ValueError(
                f"no samplable faults for seam(s) {sorted(unknown)}; "
                f"samplable: {sorted(self._samplers())}"
            )

    def _samplers(self):
        """seam site -> list of (rng, horizon) -> Fault constructors."""
        h = self.horizon

        def epoch(rng):
            return int(rng.integers(1, h))

        return {
            "iteration.epoch": [
                lambda rng: RaiseAtEpoch(epoch(rng)),
            ],
            "rank.lost": [
                # No watchdog in the soak scenario: a RankLost is a hard
                # crash, exercising the restart-resume path.
                lambda rng: RankLost(epoch(rng), rank=0),
            ],
            "checkpoint.write": [
                lambda rng: TornWrite(epoch(rng)),
            ],
            "checkpoint.committed": [
                lambda rng: KillAfterCheckpoint(min_epoch=epoch(rng)),
                lambda rng: CorruptSnapshot(
                    min_epoch=epoch(rng),
                    target=str(rng.choice(
                        ["arrays", "manifest", "truncate"])),
                ),
            ],
            "data.read": [
                lambda rng: RaiseAtRead(at_read=int(rng.integers(1, h))),
            ],
            "train.step": [
                lambda rng: NaNGrad(epoch(rng)),
                lambda rng: InfLoss(epoch(rng)),
                lambda rng: PoisonBatch(int(rng.integers(0, h))),
            ],
            # Real process deaths: each drawn crash owns a distinct
            # marker file so it fires once across orchestrator
            # restarts (the schedule index keys the directory; the
            # per-draw suffix keys multiple crashes in one schedule).
            "cluster.worker": [
                lambda rng: WorkerCrash(
                    at=epoch(rng), key="epoch",
                    exit_code=int(rng.integers(20, 30)),
                    marker=os.path.join(
                        self.marker_dir or ".",
                        f"crash-{int(rng.integers(0, 2**31))}.marker",
                    ),
                ),
            ],
            # Serving-pool gray failures: engine names drawn as bare
            # "rK" match any pool's "<pool>/rK" replica by suffix.
            "serving.replica": [
                lambda rng: ReplicaDown(
                    engine=f"r{int(rng.integers(0, self.replicas))}",
                    at_batch=epoch(rng),
                ),
                lambda rng: StallDispatch(
                    engine=f"r{int(rng.integers(0, self.replicas))}",
                    at_batch=epoch(rng),
                    delay_s=round(float(rng.uniform(0.05, 0.3)), 3),
                    for_batches=int(rng.integers(5, 40)),
                ),
                lambda rng: JitterDispatch(
                    engine=f"r{int(rng.integers(0, self.replicas))}",
                    p=round(float(rng.uniform(0.1, 0.5)), 3),
                    delay_s=round(float(rng.uniform(0.02, 0.15)), 3),
                    seed=int(rng.integers(0, 2**31)),
                ),
            ],
        }

    def sample(self, index: int) -> FaultPlan:
        """Schedule ``index`` — deterministic in ``(seed, index)``."""
        import numpy as np

        rng = np.random.default_rng([self.seed, int(index)])
        samplers = self._samplers()
        n = int(rng.integers(1, self.max_faults + 1))
        out = []
        for _ in range(n):
            seam = str(rng.choice(list(self.seams)))
            maker = samplers[seam][int(rng.integers(len(samplers[seam])))]
            out.append(maker(rng))
        return FaultPlan(*out)

    def schedules(self):
        """Yield ``(index, FaultPlan)`` for the full ``budget``."""
        for i in range(self.budget):
            yield i, self.sample(i)
