"""Self-healing training: numerics sentinel, rollback-and-quarantine
recovery, randomized chaos soak (ISSUE 9).

The pieces, composed by :func:`flinkml_tpu.iteration.iterate` (and by
the online trainers' ``fit_stream`` which thread the same knobs):

- :class:`NumericsSentinel` — a fused on-device finiteness/magnitude
  verdict over loss + carry at every epoch boundary, raising a typed
  :class:`NumericsError` classified data-poison vs systemic;
- :class:`RecoveryPolicy` + :class:`QuarantineLedger` — rollback to the
  newest valid snapshot, quarantine the offending source-batch range
  (ledgered in the snapshot ``extra`` so resume honors it), retry with
  jittered backoff;
- :mod:`flinkml_tpu.recovery.fuzz` — the randomized chaos soak:
  seeded :class:`~flinkml_tpu.faults.FuzzPlan` schedules across the
  fault seams, invariant checkers, and shrink-to-minimal-repro.

See ``docs/development/fault_tolerance.md`` ("Self-healing").
"""

from flinkml_tpu.recovery.policy import (
    ACTION_ABORT,
    ACTION_ROLLBACK_QUARANTINE,
    ACTION_STOP_AT_LAST_VALID,
    QuarantineLedger,
    RecoveryPolicy,
)
from flinkml_tpu.recovery.sentinel import (
    DATA_POISON,
    SYSTEMIC,
    NonFiniteModelError,
    NumericsError,
    NumericsSentinel,
    check_stage_finite,
)

__all__ = [
    "ACTION_ABORT",
    "ACTION_ROLLBACK_QUARANTINE",
    "ACTION_STOP_AT_LAST_VALID",
    "DATA_POISON",
    "SYSTEMIC",
    "NonFiniteModelError",
    "NumericsError",
    "NumericsSentinel",
    "QuarantineLedger",
    "RecoveryPolicy",
    "check_stage_finite",
]
