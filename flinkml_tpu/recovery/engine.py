"""The recovery session: executes a :class:`RecoveryPolicy` inside
:func:`flinkml_tpu.iteration.iterate`.

One session lives for one ``iterate`` call. When the sentinel raises,
the runtime hands the :class:`~flinkml_tpu.recovery.NumericsError` to
:meth:`RecoverySession.handle`, which either

- returns ``("retry", state, start_epoch, restored)`` — the loop rolled
  back (``restore_latest`` walk-back: a damaged rollback target falls
  one more snapshot back automatically), the offending batch is in the
  quarantine ledger, the jittered backoff has been slept — re-enter the
  epoch loop from there;
- returns ``("stop", state, start_epoch, restored)`` — the policy's
  ``stop_at_last_valid`` action: terminate with the newest valid model;
- raises — the abort action, a systemic failure, or an exhausted
  budget, always with the escalation reason in the message.

Every action is recorded in the ``recovery`` metrics group
(``rollbacks_total``, ``quarantined_batches``, per-class
``retries_total`` families, ``time_to_recover_p50_ms``/``p99_ms``).
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.recovery.policy import (
    ACTION_ABORT,
    ACTION_ROLLBACK_QUARANTINE,
    QuarantineLedger,
    RecoveryPolicy,
)
from flinkml_tpu.recovery.sentinel import (
    DATA_POISON,
    SYSTEMIC,
    NumericsError,
    NumericsSentinel,
)
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("recovery")


def _copy_state(state: Any) -> Any:
    """A pytree copy whose ARRAY leaves are owned (``np.array`` copies;
    jax arrays come to host — a one-time cost per session): neither an
    in-place-mutating step nor a later retry can reach back into it."""
    import jax

    def one(leaf):
        if isinstance(leaf, np.ndarray) or hasattr(leaf, "dtype"):
            return np.array(leaf)
        return leaf

    return jax.tree_util.tree_map(one, state)


class RecoverySession:
    """See module docstring. Created by ``iterate`` when
    ``IterationConfig.recovery`` is set; not a user-facing entry point
    (configure a :class:`RecoveryPolicy` instead)."""

    def __init__(self, policy: RecoveryPolicy, manager: Any,
                 sentinel: NumericsSentinel, ledger: QuarantineLedger,
                 init_state: Any, replayable: bool,
                 initially_restored: bool = False):
        self.policy = policy
        self.manager = manager
        self.sentinel = sentinel
        self.ledger = ledger
        self.replayable = bool(replayable)
        # Deep copy (containers AND leaves): step functions may mutate
        # the carry — or its arrays — in place, so a rollback-to-fresh
        # must hand back pristine values, not the caller's (already
        # poisoned) buffers.
        self._init_copy = _copy_state(init_state)
        # Rollback may only restore snapshots that belong to THIS run's
        # lineage: everything on disk when the run RESUMED, but nothing
        # pre-existing when it started fresh (resume=False over a dirty
        # directory must never silently resurrect a previous run's
        # model). Epochs this run commits are eligible as they land
        # (note_saved).
        self._alien_epochs = (
            set() if initially_restored or manager is None
            else set(manager.all_epochs())
        )
        self._rng = random.Random()
        self._furthest = -1
        self._no_progress = 0
        self._pinpointing = False  # last handle() started a pinpoint run
        self.rollbacks = 0
        self.retries: Dict[str, int] = {}
        self._recover_ms: List[float] = []
        self.stopped_early = False

    def note_saved(self, epoch: int) -> None:
        """The runtime committed a snapshot at ``epoch`` during this
        run — it (and any pre-existing directory it overwrote) is now a
        legitimate rollback target."""
        self._alien_epochs.discard(int(epoch))

    # -- bookkeeping ---------------------------------------------------------
    def _metrics_group(self, labels: Optional[Dict[str, str]] = None):
        from flinkml_tpu.utils.metrics import metrics

        return metrics.group("recovery", labels=labels)

    def _record_recovery(self, classification: str, t0: float) -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        self._recover_ms.append(ms)
        self.retries[classification] = (
            self.retries.get(classification, 0) + 1
        )
        g = self._metrics_group()
        g.counter("rollbacks_total")
        g.record("time_to_recover_ms", ms)
        g.gauge("time_to_recover_p50_ms",
                float(np.percentile(self._recover_ms, 50)))
        g.gauge("time_to_recover_p99_ms",
                float(np.percentile(self._recover_ms, 99)))
        self._metrics_group({"class": classification}).counter(
            "retries_total"
        )

    def summary(self) -> Dict[str, Any]:
        """The per-run recovery record attached to
        :class:`~flinkml_tpu.iteration.IterationResult.recovery`."""
        return {
            "rollbacks": self.rollbacks,
            "retries": dict(self.retries),
            "quarantined": self.ledger.indices(),
            "quarantine_ranges": self.ledger.ranges(),
            "stopped_early": self.stopped_early,
        }

    # -- the decision --------------------------------------------------------
    def _escalation_reason(self, err: NumericsError) -> Optional[str]:
        """Why a data-poison verdict must be handled as systemic (None
        when the poison path applies)."""
        if self._no_progress > self.policy.max_retries:
            return (f"no forward progress after {self._no_progress - 1} "
                    "consecutive recoveries")
        if not self.replayable:
            # Checked BEFORE the pinpoint branch: a pinpoint retry
            # re-opens the feed exactly like a quarantine retry does —
            # re-iterating a live one-shot stream would silently train
            # on a truncated tail.
            return ("the offending batch cannot be quarantined (feed is "
                    "not replayable)")
        if not err.exact:
            return None  # pinpoint retry — allowed
        if err.source_index is None:
            return ("the offending batch cannot be quarantined (the "
                    "failing step consumed no stream batch)")
        if (len(self.ledger) >= self.policy.quarantine_budget
                and err.source_index not in self.ledger):
            return (f"quarantine budget "
                    f"({self.policy.quarantine_budget}) exhausted")
        return None

    def handle(self, err: NumericsError
               ) -> Tuple[str, Any, int, bool]:
        t0 = time.perf_counter()
        prog = err.source_index if err.source_index is not None else err.epoch
        # Forward progress = any of: a failure PAST the furthest point
        # seen; a pinpoint re-run's exact re-detection (necessarily at
        # or below the inexact verdict's watermark, but localizing the
        # bad batch IS progress — the quarantine follows); an exact
        # verdict on a batch not yet in the ledger (a SECOND poison
        # inside the same interval window lands below the watermark
        # too, yet each new quarantine moves the run forward — the
        # quarantine_budget bounds this axis, not the retry count).
        pinpoint_followup = self._pinpointing and err.exact
        self._pinpointing = False
        new_quarantine = (
            err.exact and err.source_index is not None
            and err.source_index not in self.ledger
        )
        if prog > self._furthest or pinpoint_followup or new_quarantine:
            self._furthest = max(self._furthest, prog)
            self._no_progress = 1
        else:
            self._no_progress += 1

        classification = err.classification
        action = self.policy.action_for(classification)
        reason = None
        if classification == DATA_POISON \
                and action == ACTION_ROLLBACK_QUARANTINE:
            # The healing path still escalates when it cannot make
            # progress; a data_poison action the user configured as
            # abort/stop runs directly below (no quarantine).
            reason = self._escalation_reason(err)
            if reason is not None:
                classification = SYSTEMIC
                action = self.policy.action_for(SYSTEMIC)
        if action != ACTION_ROLLBACK_QUARANTINE:
            detail = f" ({reason})" if reason else ""
            if action == ACTION_ABORT:
                _log.error("recovery aborting at epoch %d: %s%s",
                           err.epoch, err, detail)
                self._metrics_group({"class": classification}).counter(
                    "aborts_total"
                )
                raise NumericsError(
                    f"unrecoverable: {err}{detail}",
                    classification=classification, epoch=err.epoch,
                    source_index=err.source_index, verdict=err.verdict,
                ) from err
            # stop_at_last_valid
            state, epoch, restored = self._rollback()
            self.stopped_early = True
            self._record_recovery(classification, t0)
            _log.warning(
                "recovery stopping at last valid snapshot (epoch %d) "
                "after %s%s", epoch, err, detail,
            )
            return ("stop", state, epoch, restored)

        # -- data-poison heal: rollback (+ quarantine when the batch is
        # known exactly; pinpoint re-run otherwise) -------------------------
        if not err.exact:
            self.sentinel.begin_pinpoint(err.epoch)
            self._pinpointing = True
            _log.warning(
                "inexact poison verdict at epoch %d (interval-checked): "
                "rolling back to pinpoint the offending batch",
                err.epoch,
            )
        else:
            if self.ledger.add(err.source_index):
                self._metrics_group().counter("quarantined_batches")
                _log.warning(
                    "quarantined source batch %d (epoch %d): %s — "
                    "ledger now %s", err.source_index, err.epoch, err,
                    self.ledger.ranges(),
                )
        state, epoch, restored = self._rollback()
        self.sentinel.reset_streak()
        delay = self.policy.backoff(self._no_progress, self._rng)
        if delay > 0:
            time.sleep(delay)
        self._record_recovery(DATA_POISON, t0)
        _log.warning(
            "recovery retry: rolled back to epoch %d (backoff %.3fs, "
            "%d rollback(s) so far)", epoch, delay, self.rollbacks,
        )
        return ("retry", state, epoch, restored)

    def _rollback(self) -> Tuple[Any, int, bool]:
        """Newest valid AND FINITE snapshot, walking back past torn and
        corrupt ones (the ``restore_latest`` ladder) and ALSO past
        snapshots holding a non-finite carry — an interval-checked
        sentinel can let a poisoned state reach a commit between checks,
        and restoring it would quarantine innocent batches forever.
        Falls back to a pristine fresh start when no snapshot survives;
        either way the rollback is LOGGED and counted — never a silent
        fresh start."""
        self.rollbacks += 1
        if self.manager is not None:
            restored = self._restore_newest_finite()
            if restored is not None:
                return restored[0], int(restored[1]), True
        _log.warning(
            "rollback found no committed finite snapshot: restarting "
            "from the initial state (epoch 0) with the quarantine "
            "ledger applied"
        )
        # Fresh deep copy per rollback: a retry's in-place mutations
        # must not reach the template either.
        return _copy_state(self._init_copy), 0, False

    def _restore_newest_finite(self) -> Optional[Tuple[Any, int]]:
        from flinkml_tpu.iteration.checkpoint import (
            CheckpointIntegrityError,
        )
        from flinkml_tpu.recovery.sentinel import _float_leaves

        for epoch in reversed(self.manager.all_epochs()):
            if epoch in self._alien_epochs:
                # A pre-existing snapshot of a previous run over the
                # same directory (this run started resume=False):
                # restoring it would silently resurrect the OLD model.
                _log.warning(
                    "rollback: skipping pre-existing snapshot epoch %s "
                    "(not part of this run — it started fresh)", epoch,
                )
                continue
            try:
                state, ep = self.manager.restore(epoch,
                                                 like=self._init_copy)
            except CheckpointIntegrityError as e:
                _log.warning(
                    "rollback: snapshot epoch %s failed verification "
                    "(%s); walking back", epoch, e,
                )
                continue
            if all(np.isfinite(leaf).all()
                   for leaf in _float_leaves(state)):
                return state, ep
            _log.warning(
                "rollback: snapshot epoch %s restored a NON-FINITE "
                "carry (committed inside a sentinel interval window); "
                "discarding it and walking back", epoch,
            )
            # Left on disk it is a time bomb: a kill before the retry
            # overwrites this epoch would hand the poisoned carry to
            # the resumed run's finiteness-UNAWARE restore_latest,
            # which then quarantines whatever batch happens to be
            # current. This run committed it, so this run removes it.
            self.manager.discard(epoch)
        return None
