"""Recovery policy + quarantine ledger: what to DO about a bad verdict.

The sentinel (:mod:`flinkml_tpu.recovery.sentinel`) turns silent
numerics damage into a typed :class:`~flinkml_tpu.recovery.sentinel
.NumericsError`; this module is the decision layer the iteration runtime
executes when one fires:

- **data-poison** → roll back to the newest VALID snapshot (the
  existing ``restore_latest`` walk-back, so a torn/corrupt rollback
  target transparently falls one more snapshot back), **quarantine**
  the offending source-batch range by advancing the feed watermark past
  it, and retry. The ledger rides every snapshot's ``extra`` manifest,
  so a kill mid-recovery resumes with the quarantine intact.
- **systemic** → no single batch to skip: the configured action (abort
  by default, or stop-at-last-valid) runs after the poison budget or
  retry budget is exhausted too, so a "poison" that keeps moving is
  escalated instead of quarantining the whole feed.

Retries back off exponentially **with jitter** (decorrelated restarts —
the same reason ``init_distributed``'s rendezvous retry jitters), and
every action is counted in the ``recovery`` metrics group
(rollbacks_total, quarantined_batches, retries by class,
time-to-recover percentiles — ``docs/development/observability.md``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Dict, List, Optional, Tuple

from flinkml_tpu.recovery.sentinel import DATA_POISON, SYSTEMIC

#: per-class actions a policy may configure
ACTION_ROLLBACK_QUARANTINE = "rollback_quarantine"
ACTION_ABORT = "abort"
ACTION_STOP_AT_LAST_VALID = "stop_at_last_valid"

_ACTIONS = (ACTION_ROLLBACK_QUARANTINE, ACTION_ABORT,
            ACTION_STOP_AT_LAST_VALID)


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Knobs of the self-healing loop (see module docstring).

    Args:
        max_retries: recoveries allowed WITHOUT forward progress (a
            retry that delivers at least one new epoch past the previous
            best resets the count) before escalating to the systemic
            action — a failure that rollback-and-quarantine cannot move
            past is systemic by definition.
        backoff_s: base of the exponential retry backoff
            (``backoff_s * 2**(attempt-1)``); 0 disables sleeping
            (tests, CI soaks).
        backoff_jitter: uniform jitter fraction added to each backoff
            (``delay * U[0, jitter]``) so retrying ranks/jobs
            decorrelate instead of re-colliding in lockstep.
        max_backoff_s: cap on a single backoff sleep.
        quarantine_budget: most source batches the engine may quarantine
            in one run; exceeding it escalates to the systemic action
            (data cannot be THAT bad — something else is wrong).
        actions: per-class override of the default actions
            (``{"data_poison": ..., "systemic": ...}``).
    """

    max_retries: int = 3
    backoff_s: float = 0.05
    backoff_jitter: float = 0.5
    max_backoff_s: float = 5.0
    quarantine_budget: int = 8
    actions: Optional[Dict[str, str]] = None

    def __post_init__(self):
        if self.max_retries < 1:
            raise ValueError(
                f"max_retries must be >= 1, got {self.max_retries}"
            )
        if self.quarantine_budget < 0:
            raise ValueError(
                "quarantine_budget must be >= 0, got "
                f"{self.quarantine_budget}"
            )
        for cls, action in (self.actions or {}).items():
            if cls not in (DATA_POISON, SYSTEMIC):
                raise ValueError(f"unknown failure class {cls!r}")
            if action not in _ACTIONS:
                raise ValueError(
                    f"unknown action {action!r} (one of {_ACTIONS})"
                )
            if cls == SYSTEMIC and action == ACTION_ROLLBACK_QUARANTINE:
                raise ValueError(
                    "systemic failures have no single batch to "
                    "quarantine; use 'abort' or 'stop_at_last_valid'"
                )

    def action_for(self, classification: str) -> str:
        defaults = {
            DATA_POISON: ACTION_ROLLBACK_QUARANTINE,
            SYSTEMIC: ACTION_ABORT,
        }
        return (self.actions or {}).get(
            classification, defaults[classification]
        )

    def backoff(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """The jittered sleep before retry ``attempt`` (1-based):
        :func:`~flinkml_tpu.parallel.distributed.retry_backoff_s` (one
        shared jittered-exponential shape with the rendezvous retry),
        capped at ``max_backoff_s``."""
        if self.backoff_s <= 0:
            return 0.0
        from flinkml_tpu.parallel.distributed import retry_backoff_s

        return min(
            retry_backoff_s(attempt, self.backoff_s,
                            jitter=self.backoff_jitter, rng=rng),
            self.max_backoff_s,
        )


class QuarantineLedger:
    """The set of quarantined SOURCE batch indices, as merged ranges.

    Indices count batches in the raw (pre-quarantine) feed order — the
    same numbering the ``train.step`` seam's ``source_index`` carries.
    The ledger rides snapshot manifests as ``extra["quarantine"]``
    (``{"ranges": [[start, end), ...]}``), so resume reconstructs the
    exact skip set, and :meth:`source_position` converts a
    delivered-batch watermark into the source watermark a reopened feed
    must fast-forward to (delivered batches + the quarantined batches
    interleaved below them).
    """

    def __init__(self, indices: Optional[Any] = None):
        self._indices: set = set(int(i) for i in (indices or ()))

    # -- membership ----------------------------------------------------------
    def __contains__(self, index: int) -> bool:
        return int(index) in self._indices

    def __len__(self) -> int:
        return len(self._indices)

    def __bool__(self) -> bool:
        return bool(self._indices)

    def indices(self) -> List[int]:
        return sorted(self._indices)

    def add(self, index: int) -> bool:
        """Quarantine one source batch; True when newly added."""
        index = int(index)
        if index < 0:
            raise ValueError(f"source index must be >= 0, got {index}")
        if index in self._indices:
            return False
        self._indices.add(index)
        return True

    # -- watermark arithmetic ------------------------------------------------
    def source_position(self, delivered: int) -> int:
        """The SOURCE watermark after ``delivered`` non-quarantined
        batches: delivered + every quarantined index below it (the
        batches that were read and discarded). This is what "advancing
        the cursor watermark past the quarantined range" resolves to on
        resume."""
        delivered = int(delivered)
        s = delivered
        while True:
            s2 = delivered + sum(1 for q in self._indices if q < s)
            if s2 == s:
                return s
            s = s2

    # -- ranges / JSON (the ``extra`` manifest transport) --------------------
    def ranges(self) -> List[Tuple[int, int]]:
        """Merged half-open ``[start, end)`` ranges, sorted."""
        out: List[Tuple[int, int]] = []
        for i in self.indices():
            if out and out[-1][1] == i:
                out[-1] = (out[-1][0], i + 1)
            else:
                out.append((i, i + 1))
        return out

    def to_json_dict(self) -> Dict[str, Any]:
        return {"ranges": [[s, e] for s, e in self.ranges()]}

    @staticmethod
    def from_json_dict(d: Optional[Dict[str, Any]]) -> "QuarantineLedger":
        ledger = QuarantineLedger()
        for start, end in (d or {}).get("ranges", ()):
            for i in range(int(start), int(end)):
                ledger._indices.add(i)
        return ledger

    def __repr__(self) -> str:
        return f"QuarantineLedger(ranges={self.ranges()})"
