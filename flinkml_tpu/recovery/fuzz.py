"""Randomized chaos soak: sampled fault schedules, invariants, shrink.

Hand-scripted fault plans only cover the interleavings someone thought
to write down. The soak samples schedules across the fault seams
(:class:`flinkml_tpu.faults.FuzzPlan` — deterministic in ``(seed,
index)``), runs a real online trainer under each one with the
self-healing machinery armed, restarts it on scripted crashes exactly
like an orchestrator would, and asserts the recovery INVARIANTS:

1. **finite** — the final model holds no non-finite value;
2. **no silent fresh start / no mis-versioned model** — the model
   version equals ``batches - quarantined`` (a resume that silently
   restarted, or a poisoned batch that silently counted, both break
   this);
3. **parity** — the final coefficients are bit-identical to the same
   stream trained WITHOUT the quarantined batches (the golden run);
4. **ledger consistent** — the quarantine ledger names exactly the
   batches the schedule's numerics faults poisoned, nothing else.

A failing schedule is **shrunk** to a minimal reproducer (greedy
delta-debugging over the fault list: drop every fault whose removal
keeps the failure) and written as a deterministic
:class:`~flinkml_tpu.faults.FaultPlan` JSON artifact
(:func:`flinkml_tpu.faults.plan_to_json`) that
:func:`flinkml_tpu.faults.plan_from_json` replays exactly.

CI runs ``tools/ci.sh``'s *chaos soak* stage: a fixed-seed soak of ≥ 25
schedules inside a wall-clock budget, plus a shrink demonstration on a
seeded failing schedule. Run it by hand::

    JAX_PLATFORMS=cpu python -m flinkml_tpu.recovery.fuzz \
        --seed 7 --budget 25 --repro-dir /tmp/repros

**Serving soak** (``--serving``): the same sample→run→shrink loop
pointed at the serving pool's gray-failure seams instead of the trainer
loop. Each schedule draws 1–3 faults over ``ReplicaDown`` /
``StallDispatch`` / ``JitterDispatch`` against a 4-replica pool serving
a pure transform under closed-loop client load, with the gray-failure
guard armed (:func:`run_serving_schedule`). Invariants:

1. **zero lost requests** — every client request succeeds within its
   bounded typed-error retry budget;
2. **zero duplicate / mis-versioned responses** — every response is
   bitwise equal to the reference transform of exactly its own rows,
   and all responses name one model version (a hedge double-count or an
   abandoned straggler leaking through would break this);
3. **p99 recovery** — after the faults clear and quarantined replicas
   rejoin, closed-loop p99 returns to ≤ 2x the pre-fault baseline
   (plus an absolute floor for timer noise).

Failing schedules shrink through the same :func:`shrink_schedule`
ddmin and commit the same ``FaultPlan`` JSON repro artifact.

**Worker soak** (``--worker``): the trainer soak's restart invariants
exercised across a REAL process boundary. Each schedule draws from the
``cluster.worker`` seam (hard ``os._exit`` mid-stream via
:class:`~flinkml_tpu.faults.WorkerCrash` — crash-once markers keep a
restarted child from dying at the same trigger forever) alongside the
in-loop numerics/crash seams; the scenario runs in a CHILD process
(:func:`run_worker_schedule`) and the parent restarts it on every
nonzero exit exactly like an orchestrator supervising a worker pool.
The invariants are the trainer soak's, now with nothing shared between
incarnations but the checkpoint directory: no silent fresh start
(model version), ledger parity, bit-exact coefficients vs golden.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from flinkml_tpu import faults as faults_mod
from flinkml_tpu.recovery.policy import RecoveryPolicy
from flinkml_tpu.recovery.sentinel import NumericsError, NumericsSentinel
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("recovery.fuzz")

#: The soak scenario (small on purpose: 25+ schedules must fit a CI
#: wall-clock budget; every jitted program is shared across schedules).
SCENARIO_BATCHES = 10
SCENARIO_ROWS = 32
SCENARIO_DIM = 4
SCENARIO_ALPHA = 0.5
SCENARIO_INTERVAL = 2
_POISON_FAULTS = ("NaNGrad", "InfLoss", "PoisonBatch")


def scenario_dataset(seed: int = 0):
    """The soak's feed: a synthetic :class:`~flinkml_tpu.data.Dataset`
    (so the ``data.read`` seam is live), deterministic in ``seed``."""
    from flinkml_tpu.data import Dataset
    from flinkml_tpu.table import Table

    true = np.arange(1.0, SCENARIO_DIM + 1.0)

    def mk(i, rng):
        x = rng.normal(size=(SCENARIO_ROWS, SCENARIO_DIM))
        return Table({
            "features": x,
            "label": (x @ true > 0).astype(np.float64),
        })

    return Dataset.synthetic(mk, SCENARIO_BATCHES, seed=seed)


def scenario_batches(seed: int = 0) -> List[Any]:
    """The same feed materialized as a list (golden runs filter it)."""
    return list(scenario_dataset(seed))


def _fit(feed, manager, resume: bool, self_heal: bool):
    from flinkml_tpu.models import OnlineLogisticRegression

    kwargs: Dict[str, Any] = {}
    if self_heal:
        kwargs["recovery"] = RecoveryPolicy(backoff_s=0.0)
        kwargs["sentinel"] = NumericsSentinel()
    return OnlineLogisticRegression().set_alpha(SCENARIO_ALPHA).fit_stream(
        feed, checkpoint_manager=manager,
        checkpoint_interval=SCENARIO_INTERVAL, resume=resume, **kwargs,
    )


class GoldenCache:
    """Golden models per exclusion set (the run with the quarantined
    batches excluded), computed lazily — most schedules share the empty
    exclusion."""

    def __init__(self, seed: int = 0):
        self._batches = scenario_batches(seed)
        self._cache: Dict[FrozenSet[int], Any] = {}

    def model(self, excluded: FrozenSet[int]):
        key = frozenset(int(i) for i in excluded)
        if key not in self._cache:
            from flinkml_tpu.models import OnlineLogisticRegression

            kept = [b for i, b in enumerate(self._batches)
                    if i not in key]
            self._cache[key] = (
                OnlineLogisticRegression().set_alpha(SCENARIO_ALPHA)
                .fit_stream(kept)
            )
        return self._cache[key]


def expected_quarantine(plan: "faults_mod.FaultPlan") -> FrozenSet[int]:
    """The batches a schedule's numerics faults poison — what a
    consistent ledger must name exactly."""
    out = set()
    for f in plan.faults:
        name = type(f).__name__
        if name in ("NaNGrad", "InfLoss"):
            out.add(int(f.at_epoch))
        elif name == "PoisonBatch":
            out.add(int(f.at_batch))
    return frozenset(i for i in out if 0 <= i < SCENARIO_BATCHES)


@dataclasses.dataclass
class ScheduleResult:
    index: int
    faults: List[str]
    ok: bool
    failures: List[str]
    restarts: int
    quarantined: List[int]
    elapsed_s: float


def run_schedule(plan: "faults_mod.FaultPlan", golden: GoldenCache,
                 data_seed: int = 0, self_heal: bool = True,
                 max_restarts: int = 10) -> Tuple[Any, List[str], int]:
    """Run the scenario under ``plan``: the trainer is restarted on
    every scripted crash (``FaultInjected`` — the orchestrator's role),
    numerics faults are healed in-loop when ``self_heal``. Returns
    ``(model_or_None, invariant_failures, restarts)``."""
    failures: List[str] = []
    model = None
    restarts = 0
    with tempfile.TemporaryDirectory(prefix="fuzz-ckpt-") as td:
        from flinkml_tpu.iteration import CheckpointManager
        from flinkml_tpu.iteration.checkpoint import (
            CheckpointIntegrityError,
        )

        manager = CheckpointManager(td, max_to_keep=10)
        with faults_mod.armed(plan):
            while True:
                try:
                    model = _fit(scenario_dataset(data_seed), manager,
                                 resume=restarts > 0, self_heal=self_heal)
                    break
                except faults_mod.FaultInjected:
                    restarts += 1
                    if restarts > max_restarts:
                        failures.append(
                            f"did not complete within {max_restarts} "
                            "restarts"
                        )
                        break
                except NumericsError as e:
                    failures.append(f"unhealed numerics failure: {e}")
                    break
        # The on-disk ledger: what the newest valid snapshot recorded
        # (what a NEXT resume would honor). read_extra is carry-shape-
        # independent; the epoch just passed verify(), so a failure
        # here is a real regression in ledger persistence — recorded as
        # an invariant failure, never a vacuously-empty disk ledger.
        recorded = None
        epoch = manager.newest_valid_epoch()
        if epoch is not None:
            try:
                recorded = manager.read_extra(epoch).get("quarantine")
            except CheckpointIntegrityError as e:
                failures.append(
                    f"snapshot {epoch} passed verify() but its extra "
                    f"manifest is unreadable: {e}"
                )
    from flinkml_tpu.recovery.policy import QuarantineLedger

    disk_ledger = QuarantineLedger.from_json_dict(recorded).indices()

    if model is not None:
        expected = expected_quarantine(plan) if self_heal else frozenset()
        summary = getattr(model, "recovery_summary", None) or {}
        quarantined = summary.get("quarantined", [])
        if not np.isfinite(model.coefficient).all():
            failures.append("final model is not finite")
        want_version = SCENARIO_BATCHES - len(expected)
        if model.model_version != want_version:
            failures.append(
                f"model version {model.model_version} != "
                f"{want_version} (batches - quarantined: silent fresh "
                "start or mis-counted poison)"
            )
        if self_heal:
            # The run's quarantines carry across restarts via the
            # snapshot ledger; the final restart's summary plus the
            # resumed skips must name exactly the poisoned batches —
            # read the union of the summary and the on-disk record.
            seen = set(quarantined) | set(disk_ledger)
            if seen != set(expected):
                failures.append(
                    f"quarantine ledger {sorted(seen)} != poisoned "
                    f"batches {sorted(expected)}"
                )
            if not set(disk_ledger) <= set(expected):
                failures.append(
                    f"on-disk ledger {disk_ledger} names batches no "
                    f"fault poisoned ({sorted(expected)})"
                )
        if not failures:
            ref = golden.model(expected)
            if not np.array_equal(model.coefficient, ref.coefficient):
                failures.append(
                    "final model != golden run with the quarantined "
                    "batches excluded"
                )
    elif not failures:
        failures.append("no model produced")
    return model, failures, restarts


def shrink_schedule(plan: "faults_mod.FaultPlan",
                    still_fails: Callable[["faults_mod.FaultPlan"], bool]
                    ) -> "faults_mod.FaultPlan":
    """Greedy delta-debugging over the fault list: drop every fault
    whose removal keeps ``still_fails`` true; repeat until stable. Each
    probe runs a FRESH plan (fired flags reset via spec round-trip), so
    probes never contaminate each other."""
    specs = [faults_mod.fault_to_spec(f) for f in plan.faults]

    def build(subset):
        return faults_mod.FaultPlan(
            *[faults_mod.fault_from_spec(dict(s)) for s in subset]
        )

    changed = True
    while changed and len(specs) > 1:
        changed = False
        for i in range(len(specs)):
            candidate = specs[:i] + specs[i + 1:]
            if still_fails(build(candidate)):
                specs = candidate
                changed = True
                break
    return build(specs)


@dataclasses.dataclass
class SoakReport:
    seed: int
    results: List[ScheduleResult]
    elapsed_s: float
    budget: int
    #: Schedules skipped because the wall-clock budget ran out (0 when
    #: the soak covered the full budget) — never silently truncated.
    skipped: int = 0

    @property
    def ok(self) -> bool:
        return self.skipped == 0 and all(r.ok for r in self.results)

    @property
    def failures(self) -> List[ScheduleResult]:
        return [r for r in self.results if not r.ok]

    def summary(self) -> str:
        n_q = sum(len(r.quarantined) for r in self.results)
        n_r = sum(r.restarts for r in self.results)
        return (
            f"chaos soak seed={self.seed}: {len(self.results)}/"
            f"{self.budget} schedules, {len(self.failures)} failed, "
            f"{n_r} restarts, {n_q} quarantined batches, "
            f"{self.elapsed_s:.1f}s"
            + (f" ({self.skipped} SKIPPED on wall budget)"
               if self.skipped else "")
        )


def run_soak(seed: int = 7, budget: int = 25,
             wall_budget_s: Optional[float] = None,
             fuzz: Optional["faults_mod.FuzzPlan"] = None,
             repro_dir: Optional[str] = None,
             data_seed: int = 0) -> SoakReport:
    """The full soak: ``budget`` sampled schedules, invariants asserted,
    every failing schedule shrunk and (when ``repro_dir`` is given)
    committed as a minimal ``FaultPlan`` JSON repro."""
    fuzz = fuzz or faults_mod.FuzzPlan(
        seed=seed, budget=budget, horizon=SCENARIO_BATCHES
    )
    golden = GoldenCache(data_seed)
    golden.model(frozenset())  # warm the jits outside the timed window
    t0 = time.perf_counter()
    results: List[ScheduleResult] = []
    skipped = 0
    for index, plan in fuzz.schedules():
        if (wall_budget_s is not None
                and time.perf_counter() - t0 > wall_budget_s):
            skipped = fuzz.budget - index
            _log.warning(
                "soak wall budget (%ss) exhausted at schedule %d/%d",
                wall_budget_s, index, fuzz.budget,
            )
            break
        st = time.perf_counter()
        descs = [f.describe() for f in plan.faults]
        _, failures, restarts = run_schedule(
            plan, golden, data_seed=data_seed
        )
        # Re-read the expected set for the record (the ledger equals it
        # on a green schedule).
        expected = sorted(expected_quarantine(plan))
        result = ScheduleResult(
            index=index, faults=descs, ok=not failures,
            failures=failures, restarts=restarts,
            quarantined=expected if not failures else [],
            elapsed_s=round(time.perf_counter() - st, 3),
        )
        results.append(result)
        if failures:
            _log.error("schedule %d FAILED %s: %s", index, descs, failures)
            if repro_dir is not None:
                minimal = shrink_schedule(
                    plan,
                    lambda p: bool(
                        run_schedule(p, golden, data_seed=data_seed)[1]
                    ),
                )
                os.makedirs(repro_dir, exist_ok=True)
                path = os.path.join(
                    repro_dir, f"fuzz_repro_seed{seed}_sched{index}.json"
                )
                with open(path, "w") as f:
                    f.write(faults_mod.plan_to_json(minimal, extra={
                        "seed": seed, "schedule": index,
                        "failures": failures,
                        "scenario": {
                            "batches": SCENARIO_BATCHES,
                            "rows": SCENARIO_ROWS,
                            "dim": SCENARIO_DIM,
                            "alpha": SCENARIO_ALPHA,
                            "checkpoint_interval": SCENARIO_INTERVAL,
                            "data_seed": data_seed,
                        },
                    }))
                _log.error("minimal repro written: %s (%d -> %d faults)",
                           path, len(plan.faults), len(minimal.faults))
        else:
            _log.info("schedule %d ok %s (restarts=%d)", index, descs,
                      restarts)
    report = SoakReport(
        seed=seed, results=results,
        elapsed_s=round(time.perf_counter() - t0, 2),
        budget=fuzz.budget, skipped=skipped,
    )
    _log.warning("%s", report.summary())
    return report


# ---------------------------------------------------------------------------
# Worker soak: the same invariants across a real process boundary
# ---------------------------------------------------------------------------

#: Exit code the child uses for an in-loop scripted crash
#: (``FaultInjected``) — distinct from WorkerCrash's sampled hard-exit
#: codes (20–29) and from real child failures.
WORKER_RESTART_EXIT = 3
WORKER_CHILD_TIMEOUT_S = 180.0


def _worker_child_main(workdir: str, resume: bool) -> int:
    """One incarnation of the soak trainer, run in its own process.

    Reads ``<workdir>/plan.json``, arms it, and runs the scenario with
    checkpoints under ``<workdir>/ckpt`` — firing the ``cluster.worker``
    seam once per batch so a sampled :class:`WorkerCrash` is a REAL
    ``os._exit`` mid-stream. An in-loop scripted crash
    (``FaultInjected``) exits :data:`WORKER_RESTART_EXIT`; success
    writes ``<workdir>/result.json`` and exits 0. The orchestrator
    (parent) restarts on any nonzero exit."""
    import json

    with open(os.path.join(workdir, "plan.json")) as f:
        raw = f.read()
    plan = faults_mod.plan_from_json(raw)
    extras = json.loads(raw)
    data_seed = int(extras.get("data_seed", 0))
    if extras.get("x64"):
        # Mirror the parent's precision: the env-var form of this flag
        # is not honored by this jax build, so the parent ships its
        # config-level setting through the plan file.
        import jax

        jax.config.update("jax_enable_x64", True)

    # Fired-flag persistence across INCARNATIONS: the in-process soak's
    # armed plan object survives its restart loop, so a scripted crash
    # fires once. Here every incarnation re-arms a fresh plan from
    # JSON, so fired flags are carried in the workdir instead —
    # WorkerCrash has its own marker file; the in-loop faults get this.
    fired_path = os.path.join(workdir, "fired.json")
    fired_idx: set = set()
    if os.path.exists(fired_path):
        with open(fired_path) as f:
            fired_idx = set(json.load(f))
    for i in fired_idx:
        plan.faults[i].fired = True

    from flinkml_tpu.iteration import CheckpointManager

    manager = CheckpointManager(os.path.join(workdir, "ckpt"),
                                max_to_keep=10)

    # The per-batch worker heartbeat, as a map op so the feed STAYS a
    # replayable Dataset (quarantine retries re-open it from the
    # cursor): where a pool worker would be serving a request, the soak
    # trainer is reading a batch. The counter is monotone across
    # replays; WorkerCrash's marker keeps each crash once-per-run.
    reads = [0]

    def heartbeat(batch):
        reads[0] += 1
        if faults_mod.ACTIVE is not None:
            faults_mod.fire("cluster.worker", epoch=reads[0] - 1)
        return batch

    feed = scenario_dataset(data_seed).map(heartbeat)
    with faults_mod.armed(plan):
        try:
            model = _fit(feed, manager, resume=resume, self_heal=True)
        except faults_mod.FaultInjected:
            fired_now = fired_idx | {
                i for i, f in enumerate(plan.faults)
                if getattr(f, "fired", False)
            }
            with open(fired_path, "w") as f:
                json.dump(sorted(fired_now), f)
            return WORKER_RESTART_EXIT
    summary = getattr(model, "recovery_summary", None) or {}
    with open(os.path.join(workdir, "result.json"), "w") as f:
        json.dump({
            "model_version": int(model.model_version),
            "coefficient": np.asarray(model.coefficient).tolist(),
            "quarantined": sorted(
                int(i) for i in summary.get("quarantined", [])
            ),
            "finite": bool(np.isfinite(model.coefficient).all()),
        }, f)
    return 0


def run_worker_schedule(plan: "faults_mod.FaultPlan", golden: GoldenCache,
                        data_seed: int = 0, max_restarts: int = 10
                        ) -> Tuple[Optional[Dict[str, Any]], List[str], int]:
    """Run one schedule with the trainer in a CHILD process and this
    process as the orchestrator: every nonzero child exit — an in-loop
    scripted crash OR a WorkerCrash hard ``os._exit`` — is answered
    with a restart (``resume=True``), sharing nothing with the previous
    incarnation but the checkpoint directory. Returns
    ``(result_dict_or_None, invariant_failures, restarts)``."""
    import json
    import subprocess
    import sys

    failures: List[str] = []
    result: Optional[Dict[str, Any]] = None
    restarts = 0
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    with tempfile.TemporaryDirectory(prefix="fuzz-worker-") as td:
        import jax

        with open(os.path.join(td, "plan.json"), "w") as f:
            f.write(faults_mod.plan_to_json(plan, extra={
                "data_seed": int(data_seed),
                "x64": bool(jax.config.jax_enable_x64),
            }))
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = os.pathsep.join(
            x for x in (repo_root, env.get("PYTHONPATH")) if x
        )
        while True:
            argv = [sys.executable, "-m", "flinkml_tpu.recovery.fuzz",
                    "--worker-child", td]
            if restarts > 0:
                argv.append("--resume")
            proc = subprocess.run(
                argv, env=env, capture_output=True, text=True,
                timeout=WORKER_CHILD_TIMEOUT_S,
            )
            if proc.returncode == 0:
                break
            restarts += 1
            if restarts > max_restarts:
                failures.append(
                    f"did not complete within {max_restarts} restarts "
                    f"(last rc={proc.returncode}); stderr tail: "
                    f"{proc.stderr[-500:]}"
                )
                break
        # The on-disk ledger, read the same way run_schedule reads it —
        # it is the only state the NEXT incarnation would honor.
        from flinkml_tpu.iteration import CheckpointManager
        from flinkml_tpu.iteration.checkpoint import (
            CheckpointIntegrityError,
        )

        recorded = None
        manager = CheckpointManager(os.path.join(td, "ckpt"),
                                    max_to_keep=10)
        epoch = manager.newest_valid_epoch()
        if epoch is not None:
            try:
                recorded = manager.read_extra(epoch).get("quarantine")
            except CheckpointIntegrityError as e:
                failures.append(
                    f"snapshot {epoch} passed verify() but its extra "
                    f"manifest is unreadable: {e}"
                )
        result_path = os.path.join(td, "result.json")
        if os.path.exists(result_path):
            with open(result_path) as f:
                result = json.load(f)
    from flinkml_tpu.recovery.policy import QuarantineLedger

    disk_ledger = QuarantineLedger.from_json_dict(recorded).indices()

    if result is not None:
        expected = expected_quarantine(plan)
        coeff = np.asarray(result["coefficient"])
        if not result["finite"] or not np.isfinite(coeff).all():
            failures.append("final model is not finite")
        want_version = SCENARIO_BATCHES - len(expected)
        if result["model_version"] != want_version:
            failures.append(
                f"model version {result['model_version']} != "
                f"{want_version} (batches - quarantined: silent fresh "
                "start across the process boundary)"
            )
        seen = set(result["quarantined"]) | set(disk_ledger)
        if seen != set(expected):
            failures.append(
                f"quarantine ledger {sorted(seen)} != poisoned "
                f"batches {sorted(expected)}"
            )
        if not set(disk_ledger) <= set(expected):
            failures.append(
                f"on-disk ledger {disk_ledger} names batches no "
                f"fault poisoned ({sorted(expected)})"
            )
        if not failures:
            ref = golden.model(expected)
            if not np.array_equal(coeff, np.asarray(ref.coefficient)):
                failures.append(
                    "final model != golden run with the quarantined "
                    "batches excluded (resume across the process "
                    "boundary diverged)"
                )
    elif not failures:
        failures.append("no result produced")
    return result, failures, restarts


def run_worker_soak(seed: int = 7, budget: int = 4,
                    wall_budget_s: Optional[float] = None,
                    fuzz: Optional["faults_mod.FuzzPlan"] = None,
                    repro_dir: Optional[str] = None,
                    data_seed: int = 0) -> SoakReport:
    """The process-boundary soak: ``budget`` schedules over the
    ``cluster.worker`` seam mixed with the in-loop crash/numerics
    seams, each run via :func:`run_worker_schedule`. Budget defaults
    small: every restart pays a full child-interpreter spin-up."""
    with tempfile.TemporaryDirectory(prefix="fuzz-markers-") as markers:
        fuzz = fuzz or faults_mod.FuzzPlan(
            seed=seed,
            seams=("cluster.worker", "iteration.epoch", "train.step"),
            budget=budget, horizon=SCENARIO_BATCHES, max_faults=2,
            marker_dir=markers,
        )
        golden = GoldenCache(data_seed)
        golden.model(frozenset())
        t0 = time.perf_counter()
        results: List[ScheduleResult] = []
        skipped = 0
        for index, plan in fuzz.schedules():
            if (wall_budget_s is not None
                    and time.perf_counter() - t0 > wall_budget_s):
                skipped = fuzz.budget - index
                _log.warning(
                    "worker soak wall budget (%ss) exhausted at "
                    "schedule %d/%d", wall_budget_s, index, fuzz.budget,
                )
                break
            st = time.perf_counter()
            descs = [f.describe() for f in plan.faults]
            _, failures, restarts = run_worker_schedule(
                plan, golden, data_seed=data_seed
            )
            expected = sorted(expected_quarantine(plan))
            results.append(ScheduleResult(
                index=index, faults=descs, ok=not failures,
                failures=failures, restarts=restarts,
                quarantined=expected if not failures else [],
                elapsed_s=round(time.perf_counter() - st, 3),
            ))
            if failures:
                _log.error("worker schedule %d FAILED %s: %s",
                           index, descs, failures)
                if repro_dir is not None:
                    minimal = shrink_schedule(
                        plan,
                        lambda p: bool(run_worker_schedule(
                            p, golden, data_seed=data_seed)[1]),
                    )
                    os.makedirs(repro_dir, exist_ok=True)
                    path = os.path.join(
                        repro_dir,
                        f"fuzz_worker_repro_seed{seed}_sched{index}.json",
                    )
                    with open(path, "w") as f:
                        f.write(faults_mod.plan_to_json(minimal, extra={
                            "seed": seed, "schedule": index,
                            "failures": failures,
                            "scenario": {
                                "kind": "worker",
                                "batches": SCENARIO_BATCHES,
                                "rows": SCENARIO_ROWS,
                                "dim": SCENARIO_DIM,
                                "alpha": SCENARIO_ALPHA,
                                "checkpoint_interval": SCENARIO_INTERVAL,
                                "data_seed": data_seed,
                            },
                        }))
                    _log.error(
                        "minimal worker repro written: %s (%d -> %d "
                        "faults)", path, len(plan.faults),
                        len(minimal.faults),
                    )
            else:
                _log.info("worker schedule %d ok %s (restarts=%d)",
                          index, descs, restarts)
        report = SoakReport(
            seed=seed, results=results,
            elapsed_s=round(time.perf_counter() - t0, 2),
            budget=fuzz.budget, skipped=skipped,
        )
    _log.warning("worker %s", report.summary())
    return report


# ---------------------------------------------------------------------------
# Serving soak: gray-failure schedules against a live replica pool
# ---------------------------------------------------------------------------

#: The serving scenario (sized so each schedule — pool spin-up, client
#: load, recovery probe — fits a few seconds of CI wall clock).
SERVING_REPLICAS = 4
SERVING_CLIENTS = 4
SERVING_REQUESTS = 25
SERVING_ROWS = 8
SERVING_DIM = 4
SERVING_BASELINE_REQUESTS = 60


def serving_grayfail_policy():
    """The soak's :class:`~flinkml_tpu.serving.GrayFailPolicy`: the
    production floors scaled down so the defense is LIVE at CPU-mesh
    latencies (sampled stalls are 50–300 ms; the default 250 ms
    abandonment floor would sleep through half of them)."""
    from flinkml_tpu.serving import GrayFailPolicy

    return GrayFailPolicy(
        attempt_floor_ms=40.0, min_attempt_samples=8,
        hedge_floor_ms=30.0,
        min_slow_samples=8, slow_trip=2, slow_clear=2,
        slow_abs_floor_ms=10.0,
        canary_interval_s=0.05, canary_timeout_ms=500.0,
        quarantine_retire_s=10.0,
        brownout=False,  # single-model pool: no SLO classes to shed
    )


def serving_scenario(seed: int = 0):
    """The serving feed: a fitted pure (elementwise, hedge-idempotent)
    transform plus every client request's features and their reference
    outputs. Elementwise on purpose — each output row depends only on
    its own input row, so the reference computed in one shot is bitwise
    comparable to pool responses regardless of how continuous batching
    coalesced or padded the requests."""
    from flinkml_tpu.models import StandardScaler
    from flinkml_tpu.table import Table

    rng = np.random.default_rng([seed, 17])
    n = SERVING_CLIENTS * SERVING_REQUESTS * SERVING_ROWS
    x = rng.normal(size=(n, SERVING_DIM))
    model = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "scaled")
        .fit(Table({"features": x[:256]}))
    )
    (ref,) = model.transform(Table({"features": x}))
    return model, x, np.asarray(ref.column("scaled"))


def _p99(samples_ms: List[float]) -> float:
    ordered = sorted(samples_ms)
    import math

    return ordered[min(len(ordered) - 1,
                       math.ceil(0.99 * len(ordered)) - 1)]


def run_serving_schedule(plan: "faults_mod.FaultPlan",
                         scenario: Optional[Tuple[Any, Any, Any]] = None,
                         data_seed: int = 0, max_retries: int = 8
                         ) -> Tuple[List[str], Dict[str, Any]]:
    """Run the closed-loop serving scenario under ``plan`` with the
    gray-failure guard armed; returns ``(invariant_failures, stats)``.

    Phases: (1) un-faulted baseline load seeds every replica's attempt
    ring and measures baseline p99; (2) the fault plan arms and
    ``SERVING_CLIENTS`` closed-loop clients each issue
    ``SERVING_REQUESTS`` requests, retrying only on TYPED backpressure
    (overload / unavailable / timeout) with bounded budget; (3) faults
    disarm, quarantined replicas are given time to canary-rejoin, and a
    recovery probe re-measures p99. Invariants per module docstring.
    """
    from flinkml_tpu.serving import (
        PoolUnavailableError,
        ReplicaPool,
        ServingConfig,
        ServingOverloadError,
        ServingTimeoutError,
        ReplicaState,
    )
    from flinkml_tpu.table import Table

    model, x, expected = scenario or serving_scenario(data_seed)
    failures: List[str] = []
    pool = ReplicaPool(
        model, Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=64, max_queue_rows=512,
                             max_wait_ms=1.0, default_timeout_ms=15_000.0),
        n_replicas=SERVING_REPLICAS, output_cols=("scaled",),
        name="soak", grayfail=serving_grayfail_policy(),
    )
    guard = pool.grayfail_guard(interval_s=0.05)
    retryable = (ServingOverloadError, PoolUnavailableError,
                 ServingTimeoutError)
    lock = threading.Lock()
    lost: List[str] = []
    mismatched: List[str] = []
    versions: set = set()
    retries = [0]
    stats: Dict[str, Any] = {}

    def one_request(sl, tag: str) -> Optional[float]:
        """One closed-loop request; parity-checked. Returns latency ms
        (None when lost after the retry budget)."""
        feats = {"features": x[sl]}
        t0 = time.perf_counter()
        for attempt in range(max_retries + 1):
            try:
                resp = pool.predict(feats, timeout_ms=5_000.0)
            except retryable:
                with lock:
                    retries[0] += 1
                time.sleep(0.01 * (attempt + 1))
                continue
            latency = (time.perf_counter() - t0) * 1e3
            got = np.asarray(resp.columns["scaled"])
            with lock:
                versions.add(resp.version)
                if not np.array_equal(got, expected[sl]):
                    mismatched.append(
                        f"{tag}: response is not the reference transform "
                        "of its own rows (duplicate/mixed/mis-versioned)"
                    )
            return latency
        with lock:
            lost.append(f"{tag}: lost after {max_retries} typed-error "
                        "retries")
        return None

    def closed_loop(client: int):
        for i in range(SERVING_REQUESTS):
            start = (client * SERVING_REQUESTS + i) * SERVING_ROWS
            lat = one_request(slice(start, start + SERVING_ROWS),
                              f"client {client} request {i}")
            if lat is not None:
                with lock:
                    faulted_ms.append(lat)
            # Think time: stretches the load window across several guard
            # evaluations so quarantine/rejoin actually happen DURING
            # traffic (a CPU-mesh request is ~1 ms; without this the
            # whole faulted phase fits inside one sampled stall).
            time.sleep(0.005)

    try:
        pool.start()
        # Phase 1: baseline (also seeds the sibling attempt rings the
        # abandonment budget needs).
        baseline_ms = []
        for i in range(SERVING_BASELINE_REQUESTS):
            start = (i % (SERVING_CLIENTS * SERVING_REQUESTS)) * SERVING_ROWS
            lat = one_request(slice(start, start + SERVING_ROWS),
                              f"baseline {i}")
            if lat is not None:
                baseline_ms.append(lat)
        if lost:
            return lost + ["baseline load lost requests; aborting"], stats
        p99_base = _p99(baseline_ms)
        # Phase 2: faulted closed-loop load.
        faulted_ms: List[float] = []
        guard.start()
        with faults_mod.armed(plan):
            threads = [
                threading.Thread(target=closed_loop, args=(c,),
                                 name=f"soak-client-{c}", daemon=True)
                for c in range(SERVING_CLIENTS)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        # Phase 3: faults disarmed — wait for SLOW replicas to
        # canary-rejoin, then probe recovered p99.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if not any(r.health.state is ReplicaState.SLOW
                       for r in pool.replicas):
                break
            time.sleep(0.05)
        still_slow = [r.name for r in pool.replicas
                      if r.health.state is ReplicaState.SLOW]
        if still_slow:
            failures.append(
                f"replicas {still_slow} never rejoined after the faults "
                "cleared (canary/rejoin path broken)"
            )
        recovered_ms = []
        for i in range(SERVING_BASELINE_REQUESTS):
            start = (i % (SERVING_CLIENTS * SERVING_REQUESTS)) * SERVING_ROWS
            lat = one_request(slice(start, start + SERVING_ROWS),
                              f"recovery {i}")
            if lat is not None:
                recovered_ms.append(lat)
        p99_rec = _p99(recovered_ms) if recovered_ms else float("inf")
        failures.extend(lost)
        failures.extend(mismatched)
        if len(versions) > 1:
            failures.append(
                f"responses named {len(versions)} distinct model "
                f"versions ({sorted(versions)}); expected exactly one"
            )
        # ≤ 2x baseline, with an absolute floor so timer noise on a
        # sub-ms baseline can't flake the invariant.
        bound = max(2.0 * p99_base, p99_base + 50.0)
        if p99_rec > bound:
            failures.append(
                f"recovered p99 {p99_rec:.1f}ms > bound {bound:.1f}ms "
                f"(baseline {p99_base:.1f}ms): pool did not recover"
            )
        per_replica = {r.name: r.health.state.value for r in pool.replicas}
        stats.update({
            "p99_baseline_ms": round(p99_base, 2),
            "p99_faulted_ms": round(_p99(faulted_ms), 2)
            if faulted_ms else None,
            "p99_recovered_ms": round(p99_rec, 2),
            "retries": retries[0],
            "replica_states": per_replica,
        })
    finally:
        guard.stop()
        pool.stop(drain=False, timeout=5.0)
    return failures, stats


@dataclasses.dataclass
class ServingScheduleResult:
    index: int
    faults: List[str]
    ok: bool
    failures: List[str]
    stats: Dict[str, Any]
    elapsed_s: float


@dataclasses.dataclass
class ServingSoakReport:
    seed: int
    results: List[ServingScheduleResult]
    elapsed_s: float
    budget: int
    skipped: int = 0

    @property
    def ok(self) -> bool:
        return self.skipped == 0 and all(r.ok for r in self.results)

    @property
    def failures(self) -> List[ServingScheduleResult]:
        return [r for r in self.results if not r.ok]

    def summary(self) -> str:
        n_retries = sum(r.stats.get("retries", 0) for r in self.results)
        return (
            f"serving soak seed={self.seed}: {len(self.results)}/"
            f"{self.budget} schedules, {len(self.failures)} failed, "
            f"{n_retries} typed-error retries, {self.elapsed_s:.1f}s"
            + (f" ({self.skipped} SKIPPED on wall budget)"
               if self.skipped else "")
        )


def run_serving_soak(seed: int = 7, budget: int = 6,
                     wall_budget_s: Optional[float] = None,
                     fuzz: Optional["faults_mod.FuzzPlan"] = None,
                     repro_dir: Optional[str] = None,
                     data_seed: int = 0) -> ServingSoakReport:
    """The serving-pool soak: ``budget`` schedules over the
    ``serving.replica`` seam, each run with :func:`run_serving_schedule`;
    failing schedules shrink through :func:`shrink_schedule` and commit
    the same JSON repro artifact as the trainer soak."""
    fuzz = fuzz or faults_mod.FuzzPlan(
        seed=seed, seams=("serving.replica",), budget=budget,
        horizon=8, max_faults=3, replicas=SERVING_REPLICAS,
    )
    scenario = serving_scenario(data_seed)
    t0 = time.perf_counter()
    results: List[ServingScheduleResult] = []
    skipped = 0
    for index, plan in fuzz.schedules():
        if (wall_budget_s is not None
                and time.perf_counter() - t0 > wall_budget_s):
            skipped = fuzz.budget - index
            _log.warning(
                "serving soak wall budget (%ss) exhausted at schedule "
                "%d/%d", wall_budget_s, index, fuzz.budget,
            )
            break
        st = time.perf_counter()
        descs = [f.describe() for f in plan.faults]
        failures, stats = run_serving_schedule(
            plan, scenario=scenario, data_seed=data_seed
        )
        results.append(ServingScheduleResult(
            index=index, faults=descs, ok=not failures,
            failures=failures, stats=stats,
            elapsed_s=round(time.perf_counter() - st, 3),
        ))
        if failures:
            _log.error("serving schedule %d FAILED %s: %s",
                       index, descs, failures)
            if repro_dir is not None:
                minimal = shrink_schedule(
                    plan,
                    lambda p: bool(run_serving_schedule(
                        p, scenario=scenario, data_seed=data_seed)[0]),
                )
                os.makedirs(repro_dir, exist_ok=True)
                path = os.path.join(
                    repro_dir,
                    f"fuzz_serving_repro_seed{seed}_sched{index}.json",
                )
                with open(path, "w") as f:
                    f.write(faults_mod.plan_to_json(minimal, extra={
                        "seed": seed, "schedule": index,
                        "failures": failures,
                        "scenario": {
                            "kind": "serving",
                            "replicas": SERVING_REPLICAS,
                            "clients": SERVING_CLIENTS,
                            "requests_per_client": SERVING_REQUESTS,
                            "rows_per_request": SERVING_ROWS,
                            "dim": SERVING_DIM,
                            "data_seed": data_seed,
                        },
                    }))
                _log.error("minimal serving repro written: %s (%d -> %d "
                           "faults)", path, len(plan.faults),
                           len(minimal.faults))
        else:
            _log.info("serving schedule %d ok %s (%s)", index, descs,
                      stats)
    report = ServingSoakReport(
        seed=seed, results=results,
        elapsed_s=round(time.perf_counter() - t0, 2),
        budget=fuzz.budget, skipped=skipped,
    )
    _log.warning("%s", report.summary())
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="flinkml_tpu chaos soak (device-free; run under "
                    "JAX_PLATFORMS=cpu)"
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--budget", type=int, default=None)
    parser.add_argument("--wall-budget-s", type=float, default=None)
    parser.add_argument("--repro-dir", default=None,
                        help="write minimal FaultPlan repros for failing "
                             "schedules here")
    parser.add_argument("--serving", action="store_true",
                        help="run the serving-pool gray-failure soak "
                             "instead of the trainer soak")
    parser.add_argument("--worker", action="store_true",
                        help="run the process-boundary worker-crash soak "
                             "(each schedule's trainer is a supervised "
                             "child process)")
    parser.add_argument("--worker-child", metavar="DIR", default=None,
                        help=argparse.SUPPRESS)  # internal: one incarnation
    parser.add_argument("--resume", action="store_true",
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)
    if args.worker_child:
        return _worker_child_main(args.worker_child, resume=args.resume)
    if args.worker:
        report = run_worker_soak(
            seed=args.seed,
            budget=args.budget if args.budget is not None else 4,
            wall_budget_s=args.wall_budget_s,
            repro_dir=args.repro_dir,
        )
    elif args.serving:
        report = run_serving_soak(
            seed=args.seed,
            budget=args.budget if args.budget is not None else 6,
            wall_budget_s=args.wall_budget_s,
            repro_dir=args.repro_dir,
        )
    else:
        report = run_soak(
            seed=args.seed,
            budget=args.budget if args.budget is not None else 25,
            wall_budget_s=args.wall_budget_s,
            repro_dir=args.repro_dir,
        )
    print(report.summary())
    for r in report.failures:
        print(f"  FAILED schedule {r.index}: {r.faults} -> {r.failures}")
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover — CLI shim
    raise SystemExit(main())
