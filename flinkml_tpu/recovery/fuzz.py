"""Randomized chaos soak: sampled fault schedules, invariants, shrink.

Hand-scripted fault plans only cover the interleavings someone thought
to write down. The soak samples schedules across the fault seams
(:class:`flinkml_tpu.faults.FuzzPlan` — deterministic in ``(seed,
index)``), runs a real online trainer under each one with the
self-healing machinery armed, restarts it on scripted crashes exactly
like an orchestrator would, and asserts the recovery INVARIANTS:

1. **finite** — the final model holds no non-finite value;
2. **no silent fresh start / no mis-versioned model** — the model
   version equals ``batches - quarantined`` (a resume that silently
   restarted, or a poisoned batch that silently counted, both break
   this);
3. **parity** — the final coefficients are bit-identical to the same
   stream trained WITHOUT the quarantined batches (the golden run);
4. **ledger consistent** — the quarantine ledger names exactly the
   batches the schedule's numerics faults poisoned, nothing else.

A failing schedule is **shrunk** to a minimal reproducer (greedy
delta-debugging over the fault list: drop every fault whose removal
keeps the failure) and written as a deterministic
:class:`~flinkml_tpu.faults.FaultPlan` JSON artifact
(:func:`flinkml_tpu.faults.plan_to_json`) that
:func:`flinkml_tpu.faults.plan_from_json` replays exactly.

CI runs ``tools/ci.sh``'s *chaos soak* stage: a fixed-seed soak of ≥ 25
schedules inside a wall-clock budget, plus a shrink demonstration on a
seeded failing schedule. Run it by hand::

    JAX_PLATFORMS=cpu python -m flinkml_tpu.recovery.fuzz \
        --seed 7 --budget 25 --repro-dir /tmp/repros
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from flinkml_tpu import faults as faults_mod
from flinkml_tpu.recovery.policy import RecoveryPolicy
from flinkml_tpu.recovery.sentinel import NumericsError, NumericsSentinel
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("recovery.fuzz")

#: The soak scenario (small on purpose: 25+ schedules must fit a CI
#: wall-clock budget; every jitted program is shared across schedules).
SCENARIO_BATCHES = 10
SCENARIO_ROWS = 32
SCENARIO_DIM = 4
SCENARIO_ALPHA = 0.5
SCENARIO_INTERVAL = 2
_POISON_FAULTS = ("NaNGrad", "InfLoss", "PoisonBatch")


def scenario_dataset(seed: int = 0):
    """The soak's feed: a synthetic :class:`~flinkml_tpu.data.Dataset`
    (so the ``data.read`` seam is live), deterministic in ``seed``."""
    from flinkml_tpu.data import Dataset
    from flinkml_tpu.table import Table

    true = np.arange(1.0, SCENARIO_DIM + 1.0)

    def mk(i, rng):
        x = rng.normal(size=(SCENARIO_ROWS, SCENARIO_DIM))
        return Table({
            "features": x,
            "label": (x @ true > 0).astype(np.float64),
        })

    return Dataset.synthetic(mk, SCENARIO_BATCHES, seed=seed)


def scenario_batches(seed: int = 0) -> List[Any]:
    """The same feed materialized as a list (golden runs filter it)."""
    return list(scenario_dataset(seed))


def _fit(feed, manager, resume: bool, self_heal: bool):
    from flinkml_tpu.models import OnlineLogisticRegression

    kwargs: Dict[str, Any] = {}
    if self_heal:
        kwargs["recovery"] = RecoveryPolicy(backoff_s=0.0)
        kwargs["sentinel"] = NumericsSentinel()
    return OnlineLogisticRegression().set_alpha(SCENARIO_ALPHA).fit_stream(
        feed, checkpoint_manager=manager,
        checkpoint_interval=SCENARIO_INTERVAL, resume=resume, **kwargs,
    )


class GoldenCache:
    """Golden models per exclusion set (the run with the quarantined
    batches excluded), computed lazily — most schedules share the empty
    exclusion."""

    def __init__(self, seed: int = 0):
        self._batches = scenario_batches(seed)
        self._cache: Dict[FrozenSet[int], Any] = {}

    def model(self, excluded: FrozenSet[int]):
        key = frozenset(int(i) for i in excluded)
        if key not in self._cache:
            from flinkml_tpu.models import OnlineLogisticRegression

            kept = [b for i, b in enumerate(self._batches)
                    if i not in key]
            self._cache[key] = (
                OnlineLogisticRegression().set_alpha(SCENARIO_ALPHA)
                .fit_stream(kept)
            )
        return self._cache[key]


def expected_quarantine(plan: "faults_mod.FaultPlan") -> FrozenSet[int]:
    """The batches a schedule's numerics faults poison — what a
    consistent ledger must name exactly."""
    out = set()
    for f in plan.faults:
        name = type(f).__name__
        if name in ("NaNGrad", "InfLoss"):
            out.add(int(f.at_epoch))
        elif name == "PoisonBatch":
            out.add(int(f.at_batch))
    return frozenset(i for i in out if 0 <= i < SCENARIO_BATCHES)


@dataclasses.dataclass
class ScheduleResult:
    index: int
    faults: List[str]
    ok: bool
    failures: List[str]
    restarts: int
    quarantined: List[int]
    elapsed_s: float


def run_schedule(plan: "faults_mod.FaultPlan", golden: GoldenCache,
                 data_seed: int = 0, self_heal: bool = True,
                 max_restarts: int = 10) -> Tuple[Any, List[str], int]:
    """Run the scenario under ``plan``: the trainer is restarted on
    every scripted crash (``FaultInjected`` — the orchestrator's role),
    numerics faults are healed in-loop when ``self_heal``. Returns
    ``(model_or_None, invariant_failures, restarts)``."""
    failures: List[str] = []
    model = None
    restarts = 0
    with tempfile.TemporaryDirectory(prefix="fuzz-ckpt-") as td:
        from flinkml_tpu.iteration import CheckpointManager
        from flinkml_tpu.iteration.checkpoint import (
            CheckpointIntegrityError,
        )

        manager = CheckpointManager(td, max_to_keep=10)
        with faults_mod.armed(plan):
            while True:
                try:
                    model = _fit(scenario_dataset(data_seed), manager,
                                 resume=restarts > 0, self_heal=self_heal)
                    break
                except faults_mod.FaultInjected:
                    restarts += 1
                    if restarts > max_restarts:
                        failures.append(
                            f"did not complete within {max_restarts} "
                            "restarts"
                        )
                        break
                except NumericsError as e:
                    failures.append(f"unhealed numerics failure: {e}")
                    break
        # The on-disk ledger: what the newest valid snapshot recorded
        # (what a NEXT resume would honor). read_extra is carry-shape-
        # independent; the epoch just passed verify(), so a failure
        # here is a real regression in ledger persistence — recorded as
        # an invariant failure, never a vacuously-empty disk ledger.
        recorded = None
        epoch = manager.newest_valid_epoch()
        if epoch is not None:
            try:
                recorded = manager.read_extra(epoch).get("quarantine")
            except CheckpointIntegrityError as e:
                failures.append(
                    f"snapshot {epoch} passed verify() but its extra "
                    f"manifest is unreadable: {e}"
                )
    from flinkml_tpu.recovery.policy import QuarantineLedger

    disk_ledger = QuarantineLedger.from_json_dict(recorded).indices()

    if model is not None:
        expected = expected_quarantine(plan) if self_heal else frozenset()
        summary = getattr(model, "recovery_summary", None) or {}
        quarantined = summary.get("quarantined", [])
        if not np.isfinite(model.coefficient).all():
            failures.append("final model is not finite")
        want_version = SCENARIO_BATCHES - len(expected)
        if model.model_version != want_version:
            failures.append(
                f"model version {model.model_version} != "
                f"{want_version} (batches - quarantined: silent fresh "
                "start or mis-counted poison)"
            )
        if self_heal:
            # The run's quarantines carry across restarts via the
            # snapshot ledger; the final restart's summary plus the
            # resumed skips must name exactly the poisoned batches —
            # read the union of the summary and the on-disk record.
            seen = set(quarantined) | set(disk_ledger)
            if seen != set(expected):
                failures.append(
                    f"quarantine ledger {sorted(seen)} != poisoned "
                    f"batches {sorted(expected)}"
                )
            if not set(disk_ledger) <= set(expected):
                failures.append(
                    f"on-disk ledger {disk_ledger} names batches no "
                    f"fault poisoned ({sorted(expected)})"
                )
        if not failures:
            ref = golden.model(expected)
            if not np.array_equal(model.coefficient, ref.coefficient):
                failures.append(
                    "final model != golden run with the quarantined "
                    "batches excluded"
                )
    elif not failures:
        failures.append("no model produced")
    return model, failures, restarts


def shrink_schedule(plan: "faults_mod.FaultPlan",
                    still_fails: Callable[["faults_mod.FaultPlan"], bool]
                    ) -> "faults_mod.FaultPlan":
    """Greedy delta-debugging over the fault list: drop every fault
    whose removal keeps ``still_fails`` true; repeat until stable. Each
    probe runs a FRESH plan (fired flags reset via spec round-trip), so
    probes never contaminate each other."""
    specs = [faults_mod.fault_to_spec(f) for f in plan.faults]

    def build(subset):
        return faults_mod.FaultPlan(
            *[faults_mod.fault_from_spec(dict(s)) for s in subset]
        )

    changed = True
    while changed and len(specs) > 1:
        changed = False
        for i in range(len(specs)):
            candidate = specs[:i] + specs[i + 1:]
            if still_fails(build(candidate)):
                specs = candidate
                changed = True
                break
    return build(specs)


@dataclasses.dataclass
class SoakReport:
    seed: int
    results: List[ScheduleResult]
    elapsed_s: float
    budget: int
    #: Schedules skipped because the wall-clock budget ran out (0 when
    #: the soak covered the full budget) — never silently truncated.
    skipped: int = 0

    @property
    def ok(self) -> bool:
        return self.skipped == 0 and all(r.ok for r in self.results)

    @property
    def failures(self) -> List[ScheduleResult]:
        return [r for r in self.results if not r.ok]

    def summary(self) -> str:
        n_q = sum(len(r.quarantined) for r in self.results)
        n_r = sum(r.restarts for r in self.results)
        return (
            f"chaos soak seed={self.seed}: {len(self.results)}/"
            f"{self.budget} schedules, {len(self.failures)} failed, "
            f"{n_r} restarts, {n_q} quarantined batches, "
            f"{self.elapsed_s:.1f}s"
            + (f" ({self.skipped} SKIPPED on wall budget)"
               if self.skipped else "")
        )


def run_soak(seed: int = 7, budget: int = 25,
             wall_budget_s: Optional[float] = None,
             fuzz: Optional["faults_mod.FuzzPlan"] = None,
             repro_dir: Optional[str] = None,
             data_seed: int = 0) -> SoakReport:
    """The full soak: ``budget`` sampled schedules, invariants asserted,
    every failing schedule shrunk and (when ``repro_dir`` is given)
    committed as a minimal ``FaultPlan`` JSON repro."""
    fuzz = fuzz or faults_mod.FuzzPlan(
        seed=seed, budget=budget, horizon=SCENARIO_BATCHES
    )
    golden = GoldenCache(data_seed)
    golden.model(frozenset())  # warm the jits outside the timed window
    t0 = time.perf_counter()
    results: List[ScheduleResult] = []
    skipped = 0
    for index, plan in fuzz.schedules():
        if (wall_budget_s is not None
                and time.perf_counter() - t0 > wall_budget_s):
            skipped = fuzz.budget - index
            _log.warning(
                "soak wall budget (%ss) exhausted at schedule %d/%d",
                wall_budget_s, index, fuzz.budget,
            )
            break
        st = time.perf_counter()
        descs = [f.describe() for f in plan.faults]
        _, failures, restarts = run_schedule(
            plan, golden, data_seed=data_seed
        )
        # Re-read the expected set for the record (the ledger equals it
        # on a green schedule).
        expected = sorted(expected_quarantine(plan))
        result = ScheduleResult(
            index=index, faults=descs, ok=not failures,
            failures=failures, restarts=restarts,
            quarantined=expected if not failures else [],
            elapsed_s=round(time.perf_counter() - st, 3),
        )
        results.append(result)
        if failures:
            _log.error("schedule %d FAILED %s: %s", index, descs, failures)
            if repro_dir is not None:
                minimal = shrink_schedule(
                    plan,
                    lambda p: bool(
                        run_schedule(p, golden, data_seed=data_seed)[1]
                    ),
                )
                os.makedirs(repro_dir, exist_ok=True)
                path = os.path.join(
                    repro_dir, f"fuzz_repro_seed{seed}_sched{index}.json"
                )
                with open(path, "w") as f:
                    f.write(faults_mod.plan_to_json(minimal, extra={
                        "seed": seed, "schedule": index,
                        "failures": failures,
                        "scenario": {
                            "batches": SCENARIO_BATCHES,
                            "rows": SCENARIO_ROWS,
                            "dim": SCENARIO_DIM,
                            "alpha": SCENARIO_ALPHA,
                            "checkpoint_interval": SCENARIO_INTERVAL,
                            "data_seed": data_seed,
                        },
                    }))
                _log.error("minimal repro written: %s (%d -> %d faults)",
                           path, len(plan.faults), len(minimal.faults))
        else:
            _log.info("schedule %d ok %s (restarts=%d)", index, descs,
                      restarts)
    report = SoakReport(
        seed=seed, results=results,
        elapsed_s=round(time.perf_counter() - t0, 2),
        budget=fuzz.budget, skipped=skipped,
    )
    _log.warning("%s", report.summary())
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="flinkml_tpu chaos soak (device-free; run under "
                    "JAX_PLATFORMS=cpu)"
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--budget", type=int, default=25)
    parser.add_argument("--wall-budget-s", type=float, default=None)
    parser.add_argument("--repro-dir", default=None,
                        help="write minimal FaultPlan repros for failing "
                             "schedules here")
    args = parser.parse_args(argv)
    report = run_soak(seed=args.seed, budget=args.budget,
                      wall_budget_s=args.wall_budget_s,
                      repro_dir=args.repro_dir)
    print(report.summary())
    for r in report.failures:
        print(f"  FAILED schedule {r.index}: {r.faults} -> {r.failures}")
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover — CLI shim
    raise SystemExit(main())
