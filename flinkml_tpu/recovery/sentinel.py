"""Numerics sentinel: a fused on-device finiteness/magnitude verdict.

A NaN'd model trains silently to garbage: every downstream update of a
non-finite carry stays non-finite, the loop keeps consuming batches, and
the damage is only discovered at serve time (if ever). The sentinel
closes that gap at the cheapest possible point — the epoch boundary the
loop already synchronizes at:

- **one fused jitted reduction** over the loss and every float leaf of
  the loop carry produces a single int32 verdict bitmask on device
  (finiteness of the loss, finiteness of the state, a magnitude bound);
- **one scalar transfer** pulls the verdict to the host. Loops that
  already sync a host criteria every epoch (the online trainers pull
  ``float(loss)``) pay only the tiny fused reduction — no new sync
  point is introduced;
- a bad verdict raises a typed :class:`NumericsError` **before** the
  poisoned state can be checkpointed, published, or served, classified
  as *data-poison* (non-finite loss/state right after a step — one bad
  batch) vs *systemic* (a finite but exploding magnitude persisting
  ``systemic_streak`` consecutive checks — divergence no single batch
  explains).

Thread it through :func:`flinkml_tpu.iteration.iterate` via
``IterationConfig(sentinel=NumericsSentinel())`` (the online trainers
expose the same knob on ``fit_stream``) or through the plan-sharded
trainer via ``train_linear_plan(..., sentinel=...)``. Pair it with a
:class:`~flinkml_tpu.recovery.RecoveryPolicy` and the raise becomes a
self-healing rollback-and-quarantine instead of a crash
(``docs/development/fault_tolerance.md``, "Self-healing").

The registry/serving side of the same contract lives here too:
:func:`check_stage_finite` refuses a non-finite model at
``ModelRegistry.publish`` and at ``ServingEngine`` model install.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import numpy as np


# verdict bitmask (host-decoded from the device scalar)
VERDICT_LOSS_NONFINITE = 1
VERDICT_STATE_NONFINITE = 2
VERDICT_MAGNITUDE = 4

#: classification values carried by :class:`NumericsError`
DATA_POISON = "data_poison"
SYSTEMIC = "systemic"


class NumericsError(RuntimeError):
    """The sentinel's typed verdict: training numerics went bad.

    Attributes:
        classification: :data:`DATA_POISON` (non-finite loss/state right
            after a step — one bad batch; rollback + quarantine heals
            it) or :data:`SYSTEMIC` (persistent divergence — a bad
            hyperparameter, a broken kernel, or a poison budget
            exhausted; no single batch to quarantine).
        epoch: the delivered-batch epoch the verdict fired at.
        source_index: the SOURCE index of the batch consumed at that
            epoch (what a quarantine excludes) — None when unknown.
        verdict: the raw bitmask (VERDICT_* flags).
        exact: False when the sentinel checks on an interval > 1 and the
            offending batch is only known to lie in ``(last_clean,
            epoch]`` — the recovery engine then rolls back and re-runs
            with per-epoch checks to pinpoint it before quarantining.
    """

    def __init__(self, message: str, classification: str, epoch: int,
                 source_index: Optional[int] = None, verdict: int = 0,
                 exact: bool = True):
        super().__init__(message)
        self.classification = classification
        self.epoch = int(epoch)
        self.source_index = (None if source_index is None
                             else int(source_index))
        self.verdict = int(verdict)
        self.exact = bool(exact)


class NonFiniteModelError(NumericsError):
    """A model with non-finite parameters reached a publish/serve
    boundary — refused before it can be swapped into a live engine or
    recorded as a registry version."""

    def __init__(self, message: str):
        super().__init__(message, classification=DATA_POISON, epoch=-1)


@functools.lru_cache(maxsize=None)
def _verdict_fn():
    """The fused verdict program: float leaves + loss -> int32 bitmask.

    jit retraces once per (leaf count, shapes, dtypes) — i.e. once per
    training run — and the whole check is a handful of reductions fused
    into one tiny program, so the armed cost is one dispatch + one
    scalar device->host transfer per checked epoch.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def verdict(leaves, loss, max_abs):
        loss_ok = jnp.isfinite(loss)
        state_ok = jnp.bool_(True)
        mag = jnp.float32(0.0)
        for leaf in leaves:
            state_ok = state_ok & jnp.all(jnp.isfinite(leaf))
            mag = jnp.maximum(
                mag, jnp.max(jnp.abs(leaf)).astype(jnp.float32)
            )
        bits = jnp.where(loss_ok, 0, VERDICT_LOSS_NONFINITE)
        bits = bits | jnp.where(state_ok, 0, VERDICT_STATE_NONFINITE)
        bits = bits | jnp.where(
            mag <= jnp.float32(max_abs), 0, VERDICT_MAGNITUDE
        )
        return bits.astype(jnp.int32)

    return verdict


def _float_leaves(state: Any):
    import jax

    return tuple(
        leaf for leaf in jax.tree_util.tree_leaves(state)
        if hasattr(leaf, "dtype")
        and np.issubdtype(np.dtype(leaf.dtype), np.floating)
    )


class NumericsSentinel:
    """See module docstring.

    Args:
        max_abs: magnitude bound over the state's float leaves; a finite
            state exceeding it for ``systemic_streak`` consecutive
            checks is classified :data:`SYSTEMIC` divergence. ``None``
            disables the magnitude check (finiteness only).
        systemic_streak: consecutive over-magnitude checks before the
            systemic raise (1 = immediately).
        interval: check every N epochs (1 = every epoch). With N > 1 a
            detection is *inexact* — the bad batch lies somewhere in the
            unchecked window — and the raise carries ``exact=False`` so
            the recovery engine re-runs the window with per-epoch checks
            to pinpoint it (``begin_pinpoint``).
    """

    def __init__(self, max_abs: Optional[float] = 1e8,
                 systemic_streak: int = 3, interval: int = 1):
        if interval < 1:
            raise ValueError(f"interval must be >= 1, got {interval}")
        if systemic_streak < 1:
            raise ValueError(
                f"systemic_streak must be >= 1, got {systemic_streak}"
            )
        self.max_abs = None if max_abs is None else float(max_abs)
        self.systemic_streak = int(systemic_streak)
        self.interval = int(interval)
        self._mag_streak = 0
        self._last_clean_epoch: Optional[int] = None
        self._pinpoint_until: Optional[int] = None
        #: epochs checked / raises, for tests and the recovery metrics
        self.checks = 0
        self.raises = 0

    # -- recovery-engine hooks ----------------------------------------------
    def begin_pinpoint(self, until_epoch: int) -> None:
        """Force per-epoch checks through ``until_epoch`` (inclusive) —
        the re-run after an inexact interval>1 detection."""
        self._pinpoint_until = int(until_epoch)

    def reset_streak(self) -> None:
        """Forget magnitude-streak state (called after a rollback: the
        restored carry predates the streak)."""
        self._mag_streak = 0
        self._last_clean_epoch = None

    def _due(self, epoch: int) -> bool:
        if self._pinpoint_until is not None:
            if epoch <= self._pinpoint_until:
                return True
            self._pinpoint_until = None
        return self.interval == 1 or (epoch + 1) % self.interval == 0

    # -- the check -----------------------------------------------------------
    def check(self, state: Any, criteria: Optional[float], epoch: int,
              source_index: Optional[int] = None) -> None:
        """Verdict over the post-step ``state`` (+ the step's loss, when
        it returned one); raises :class:`NumericsError` on a bad one.
        Call at the epoch boundary, BEFORE the state is checkpointed or
        handed to listeners."""
        if not self._due(epoch):
            return
        leaves = _float_leaves(state)
        loss = 0.0 if criteria is None else criteria
        max_abs = self.max_abs if self.max_abs is not None else np.inf
        if leaves:
            bits = int(_verdict_fn()(leaves, float(loss), float(max_abs)))
        else:  # host-only carry with no float arrays: loss check only
            bits = 0 if np.isfinite(loss) else VERDICT_LOSS_NONFINITE
        self.checks += 1
        exact = (
            self.interval == 1
            or self._pinpoint_until is not None
            or self._last_clean_epoch == epoch - 1
        )
        if bits & (VERDICT_LOSS_NONFINITE | VERDICT_STATE_NONFINITE):
            self.raises += 1
            what = []
            if bits & VERDICT_LOSS_NONFINITE:
                what.append("loss")
            if bits & VERDICT_STATE_NONFINITE:
                what.append("state")
            raise NumericsError(
                f"non-finite {'/'.join(what)} at epoch {epoch} "
                f"(source batch "
                f"{'?' if source_index is None else source_index}"
                f"{'' if exact else ', inexact: interval-checked'})",
                classification=DATA_POISON, epoch=epoch,
                source_index=source_index, verdict=bits, exact=exact,
            )
        if bits & VERDICT_MAGNITUDE:
            self._mag_streak += 1
            if self._mag_streak >= self.systemic_streak:
                self.raises += 1
                raise NumericsError(
                    f"state magnitude exceeded {self.max_abs:g} for "
                    f"{self._mag_streak} consecutive checks (epoch "
                    f"{epoch}) — systemic divergence, not a single bad "
                    "batch",
                    classification=SYSTEMIC, epoch=epoch,
                    source_index=source_index,
                    verdict=bits, exact=exact,
                )
        else:
            self._mag_streak = 0
            self._last_clean_epoch = epoch


# -- publish/serve boundary --------------------------------------------------


def _iter_stage_arrays(stage: Any):
    """Yield ``(name, array)`` for every float array a stage's model
    data exposes. Pipelines recurse into their stages; stages without a
    ``get_model_data`` surface (pure transforms — no learned arrays)
    yield nothing."""
    stages = getattr(stage, "stages", None)
    if stages is not None and not callable(stages):
        for i, sub in enumerate(stages):
            for name, arr in _iter_stage_arrays(sub):
                yield f"stage[{i}].{name}", arr
        return
    get_model_data = getattr(stage, "get_model_data", None)
    if get_model_data is None:
        return
    try:
        tables = get_model_data()
    except ValueError:
        return  # no model data set — nothing to verify
    for t, table in enumerate(tables):
        for col in getattr(table, "column_names", ()):
            arr = np.asarray(table.column(col))
            if np.issubdtype(arr.dtype, np.floating):
                yield f"model_data[{t}].{col}", arr


def check_stage_finite(stage: Any, where: str = "publish") -> None:
    """Refuse a non-finite model at a publish/serve boundary: raises
    :class:`NonFiniteModelError` naming the first bad array. Stages
    without learned arrays pass trivially."""
    for name, arr in _iter_stage_arrays(stage):
        if not np.isfinite(arr).all():
            bad = int(np.size(arr) - np.isfinite(arr).sum())
            raise NonFiniteModelError(
                f"refusing to {where} {type(stage).__name__}: model "
                f"array {name!r} holds {bad} non-finite value(s) — a "
                "NaN'd model must never reach serving (roll back to the "
                "newest valid snapshot / registry version; see "
                "docs/development/fault_tolerance.md, 'Self-healing')"
            )
