"""Knob searches: measure every candidate through the PRODUCT path.

Each ``measure_*`` function runs a compact version of the bench
harness's corresponding stage — same trainers, same gates, smaller
shapes — and returns ``{candidate: measured_value}`` in the knob's unit
(throughput; higher is better). :func:`settle` converts measurements
into a committed default under the **decisive-win hysteresis rule**: the
static default keeps its seat unless a challenger beats it by more than
:data:`RATIO_FLOOR` (1.10x), so run-to-run measurement noise can never
flip-flop a committed default — exactly the "measured, not guessed, and
not noise either" discipline VERDICT's sort-class item asks for.

The layout knobs are driven through their existing env-var gates
(``FLINKML_TPU_SPARSE_LAYOUT`` etc.), so the search measures precisely
the code path a user selecting that candidate would run.

``quick=True`` shrinks every scenario to smoke-test size (CI and unit
tests); committed numbers should come from a full run
(``python -m flinkml_tpu.autotune --commit``).
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flinkml_tpu.autotune.table import KNOWN_KNOBS, TuningTable, mesh_key
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("autotune")

#: A challenger must beat the incumbent by this ratio to take the
#: default (see module docstring).
RATIO_FLOOR = 1.10

#: The static (pre-autotune) defaults — the incumbents hysteresis
#: protects, and the fallbacks consumers use when a mesh has no entry.
STATIC_DEFAULTS: Dict[str, Any] = {
    "sparse_layout": "unsorted",
    "gbt_histogram": "segment",
    "als_reduction": "segment",
    "w2v_accum": "scatter",
    "infer_plan_order": ["batch_parallel", "fsdp", "fsdp_tp"],
    "serving_max_batch_rows": 1024,
    "serving_window_ms": 2.0,
    "kernel_backend_fused_chain": "xla",
    "kernel_backend_segment_sum": "xla",
    "kernel_backend_spmv": "xla",
    "kernel_backend_topk": "xla",
    "embedding_exchange": "ring",
    "serving_scale_up_backlog": 0.5,
    "int8_min_const_elems": 16,
}


@contextlib.contextmanager
def _env(var: str, value: str):
    prev = os.environ.get(var)
    os.environ[var] = value
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = prev


def settle(knob: str, candidates: Dict[str, float],
           incumbent: Any = None) -> Any:
    """The winner under the hysteresis rule. ``candidates`` maps the
    candidate's string form to its measured value; the returned winner
    keeps the candidate's native type for the two numeric knobs.

    ``incumbent`` is the value defending its seat — the CURRENTLY
    COMMITTED table value when one exists (a win near the floor must
    not flip-flop on every re-measure: once committed, the challenger
    becomes the incumbent and reverting needs its own decisive win),
    else the static default."""
    default = STATIC_DEFAULTS[knob]
    if incumbent is None:
        incumbent = default
    best = max(candidates, key=candidates.get)
    seat = str(incumbent)
    if seat in candidates and candidates[best] <= \
            candidates[seat] * RATIO_FLOOR:
        best = seat
    if isinstance(default, int) and not isinstance(default, bool):
        return int(best)
    if isinstance(default, float):
        return float(best)
    return best


def _timed_rate(fn: Callable[[], float]) -> float:
    """Best-of-2 of a self-reporting rate measurement (the second rep
    absorbs scheduler jitter on a shared box; compiles happen before
    either via the caller's warmup)."""
    return max(fn(), fn())


# -- the four sort-class layout knobs ----------------------------------------


def measure_sparse_layout(quick: bool = False) -> Dict[str, float]:
    """Sparse-LR samples/s per gradient layout (the
    ``make_sparse_step_bucketed`` A/B, Criteo-profile data)."""
    import jax.numpy as jnp

    from flinkml_tpu.models import _linear_sgd
    from flinkml_tpu.parallel import DeviceMesh

    n, dim, nnz = (8_192, 65_536, 16) if quick else (32_768, 262_144, 24)
    steps = 20 if quick else 100
    rng = np.random.default_rng(0)
    indptr = np.arange(n + 1, dtype=np.int64) * nnz
    indices = rng.integers(0, dim, size=n * nnz).astype(np.int32)
    values = rng.normal(size=n * nnz).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    mesh = DeviceMesh()
    p = mesh.axis_size()
    out: Dict[str, float] = {}
    for layout in _linear_sgd._SPARSE_LAYOUTS:
        with _env("FLINKML_TPU_SPARSE_LAYOUT", layout):
            data_args, local_bss = _linear_sgd.prepare_sparse_buckets(
                indptr, indices, values, dim, y, w, mesh, n,
                seed=0, layout=layout,
            )
            trainer = _linear_sgd._sparse_trainer_bucketed(
                mesh.mesh, "logistic", local_bss, DeviceMesh.DATA_AXIS,
                int(dim), layout,
            )
            f32 = lambda v: jnp.asarray(v, jnp.float32)  # noqa: E731
            carry0 = (jnp.zeros(dim, jnp.float32),
                      jnp.asarray(0, jnp.int32),
                      jnp.asarray(jnp.inf, jnp.float32))
            hy = (f32(0.1), f32(0.0), f32(0.0), f32(0.0))
            np.asarray(trainer(*carry0, *data_args, *hy,
                               jnp.asarray(2, jnp.int32))[0])  # warmup

            def rate() -> float:
                t0 = time.perf_counter()
                coef, steps_out, _ = trainer(
                    *carry0, *data_args, *hy, jnp.asarray(steps, jnp.int32)
                )
                np.asarray(coef)
                return sum(local_bss) * p * int(steps_out) / (
                    time.perf_counter() - t0
                )

            out[layout] = _timed_rate(rate)
    return out


def measure_gbt_histogram(quick: bool = False) -> Dict[str, float]:
    """GBT row-tree builds/s per histogram layout (whole-forest
    builder, the ``FLINKML_TPU_GBT_HISTOGRAM`` A/B)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.models.gbt import (
        _forest_builder, _hist_layout, bin_features, quantile_bin_edges,
        sharded_hist_args,
    )
    from flinkml_tpu.parallel import DeviceMesh

    n, d, bins, depth, trees = (
        (8_192, 8, 16, 3, 4) if quick else (65_536, 16, 32, 4, 10)
    )
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, size=(n, d)).astype(np.float32)
    y = (x[:, 0] * x[:, 1] > 0).astype(np.float32)
    w = np.ones(n, dtype=np.float32)
    edges = quantile_bin_edges(x, bins)
    binned = bin_features(x, edges)
    mesh = DeviceMesh()
    f32 = lambda v: jnp.asarray(v, jnp.float32)  # noqa: E731
    out: Dict[str, float] = {}
    for layout in ("segment", "cumsum"):
        with _env("FLINKML_TPU_GBT_HISTOGRAM", layout):
            assert _hist_layout() == layout
            builder = _forest_builder(
                mesh.mesh, DeviceMesh.DATA_AXIS, d, bins, depth, trees,
                True, hist_layout=layout,
            )
            hist_args = sharded_hist_args(binned, mesh, bins, layout)
            args = (
                mesh.shard_batch(binned), mesh.shard_batch(y),
                mesh.shard_batch(w), f32(0.0), f32(0.2), f32(1.0),
                f32(1.0), jax.random.PRNGKey(0),
            ) + hist_args
            np.asarray(builder(*args)[2])  # compile + warmup

            def rate() -> float:
                t0 = time.perf_counter()
                np.asarray(builder(*args)[2])
                return n * trees / (time.perf_counter() - t0)

            out[layout] = _timed_rate(rate)
    return out


def measure_als_reduction(quick: bool = False) -> Dict[str, float]:
    """ALS rating visits/s per reduction layout through the product
    ``ALS.fit`` (the ``FLINKML_TPU_ALS_REDUCTION`` A/B)."""
    from flinkml_tpu.models.als import ALS
    from flinkml_tpu.table import Table

    users_n, items_n, nnz, rank, iters = (
        (1_024, 1_024, 1 << 14, 8, 2) if quick
        else (4_096, 4_096, 1 << 18, 16, 4)
    )
    rng = np.random.default_rng(0)
    table = Table({
        "user": rng.integers(0, users_n, size=nnz).astype(np.int32),
        "item": rng.integers(0, items_n, size=nnz).astype(np.int32),
        "rating": rng.uniform(1, 5, size=nnz).astype(np.float32),
    })
    out: Dict[str, float] = {}
    for layout in ("segment", "cumsum"):
        with _env("FLINKML_TPU_ALS_REDUCTION", layout):
            ALS().set_rank(rank).set_max_iter(1).set_seed(0).fit(table)

            def rate() -> float:
                t0 = time.perf_counter()
                ALS().set_rank(rank).set_max_iter(iters).set_seed(0).fit(
                    table
                )
                return nnz * 2 * iters / (time.perf_counter() - t0)

            out[layout] = _timed_rate(rate)
    return out


def measure_w2v_accum(quick: bool = False) -> Dict[str, float]:
    """Word2Vec (center, context) pairs/s per embedding-gradient
    accumulation layout (the ``FLINKML_TPU_W2V_ACCUM`` A/B)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.models.word2vec import _sgns_trainer
    from flinkml_tpu.parallel import DeviceMesh

    vocab, dim, n_pairs, bs, n_neg, steps = (
        (2_048, 32, 1 << 14, 1_024, 3, 20) if quick
        else (8_192, 64, 1 << 17, 4_096, 5, 60)
    )
    rng = np.random.default_rng(0)
    centers = rng.integers(0, vocab, size=n_pairs).astype(np.int32)
    contexts = rng.integers(0, vocab, size=n_pairs).astype(np.int32)
    weights = np.ones(n_pairs, np.float32)
    pool = rng.integers(0, vocab, size=1 << 14).astype(np.int32)
    v0 = (rng.random((vocab, dim)) - 0.5).astype(np.float32) / dim
    u0 = np.zeros((vocab, dim), np.float32)
    mesh = DeviceMesh()
    local_bs = max(1, bs // mesh.axis_size())
    key = jax.random.PRNGKey(0)
    out: Dict[str, float] = {}
    for accum in ("scatter", "onehot"):
        with _env("FLINKML_TPU_W2V_ACCUM", accum):
            trainer = _sgns_trainer(
                mesh.mesh, DeviceMesh.DATA_AXIS, local_bs, n_neg, accum
            )
            args = (
                mesh.shard_batch(centers), mesh.shard_batch(contexts),
                mesh.shard_batch(weights),
                jnp.asarray(pool), jnp.asarray(v0), jnp.asarray(u0),
                jnp.asarray(0.025, jnp.float32),
            )
            np.asarray(trainer(*args, jnp.asarray(2, jnp.int32), key)[0])

            def rate() -> float:
                t0 = time.perf_counter()
                np.asarray(
                    trainer(*args, jnp.asarray(steps, jnp.int32), key)[0]
                )
                return local_bs * mesh.axis_size() * steps / (
                    time.perf_counter() - t0
                )

            out[accum] = _timed_rate(rate)
    return out


# -- infer_plan preset order -------------------------------------------------


def measure_infer_plan_order(quick: bool = False) -> Dict[str, float]:
    """Plan-sharded trainer samples/s per preset — what turns
    ``infer_plan``'s guessed ascending-communication-cost order into a
    measured one."""
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding.apply import train_linear_plan
    from flinkml_tpu.sharding.plan import PRESETS

    n, dim, iters = (4_096, 128, 8) if quick else (16_384, 512, 24)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x @ rng.normal(size=dim).astype(np.float32) > 0).astype(np.float32)
    out: Dict[str, float] = {}
    for name in STATIC_DEFAULTS["infer_plan_order"]:
        plan = PRESETS[name]
        mesh = DeviceMesh.for_plan(plan)
        train_linear_plan(x, y, None, plan, mesh, max_iter=2)  # warmup

        def rate() -> float:
            t0 = time.perf_counter()
            train_linear_plan(x, y, None, plan, mesh, max_iter=iters)
            return n * iters / (time.perf_counter() - t0)

        out[name] = _timed_rate(rate)
    return out


def order_presets(candidates: Dict[str, float]) -> List[str]:
    """The measured ``infer_plan`` candidate order: start from the
    static ascending-communication-cost order and promote a preset past
    a cheaper one only on a decisive (>: data:`RATIO_FLOOR`) throughput
    win — ties keep the static (cheapest-communication) order."""
    order: List[str] = []
    for name in STATIC_DEFAULTS["infer_plan_order"]:
        pos = len(order)
        while pos > 0 and candidates.get(name, 0.0) > \
                candidates.get(order[pos - 1], 0.0) * RATIO_FLOOR:
            pos -= 1
        order.insert(pos, name)
    return order


# -- serving bucket cap + batching window ------------------------------------


def _serving_model():
    """A small fused all-kernel chain (scaler → logistic) + example."""
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import StandardScaler
    from flinkml_tpu.pipeline import PipelineModel
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2_048, 16))
    y = (x @ rng.normal(size=16) > 0).astype(np.float64)
    train = Table({"features": x, "label": y})
    scaler = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
              .set(StandardScaler.OUTPUT_COL, "scaled").fit(train))
    (scaled,) = scaler.transform(train)
    lr = (LogisticRegression()
          .set(LogisticRegression.FEATURES_COL, "scaled")
          .set(LogisticRegression.LABEL_COL, "label")
          .set_max_iter(2).fit(scaled))
    return PipelineModel([scaler, lr]), x


def _closed_loop_rate(model, x, max_batch_rows: int, window_ms: float,
                      duration_s: float, n_clients: int = 4) -> float:
    """Closed-loop serving rows/s at the given knob values."""
    import threading

    from flinkml_tpu.serving.engine import ServingConfig, ServingEngine
    from flinkml_tpu.table import Table

    example = Table({"features": x[:4], "label": np.zeros(4)})
    engine = ServingEngine(
        model, example,
        ServingConfig(max_batch_rows=max_batch_rows, max_wait_ms=window_ms,
                      max_queue_rows=max(8_192, 4 * max_batch_rows)),
        name=f"autotune-{max_batch_rows}-{window_ms}",
    ).start()
    rows_done = [0] * n_clients
    stop = threading.Event()
    rng = np.random.default_rng(1)

    def client(tid: int) -> None:
        while not stop.is_set():
            rows = int(rng.integers(1, 65))
            try:
                engine.predict({"features": x[:rows],
                                "label": np.zeros(rows)})
            except Exception:  # noqa: BLE001 — overload: keep offering
                continue
            rows_done[tid] += rows

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    elapsed = time.perf_counter() - t0
    engine.stop(drain=False)
    return sum(rows_done) / elapsed


def measure_serving_max_batch_rows(quick: bool = False) -> Dict[str, float]:
    """Closed-loop serving rows/s per power-of-two dispatch bucket cap
    (fixed 2 ms window — the static default)."""
    model, x = _serving_model()
    duration = 0.6 if quick else 2.0
    caps = (256, 1024) if quick else (256, 512, 1024, 2048)
    return {
        str(cap): _closed_loop_rate(model, x, cap, 2.0, duration)
        for cap in caps
    }


def measure_serving_window_ms(quick: bool = False) -> Dict[str, float]:
    """Closed-loop serving rows/s per batching window (fixed 1024-row
    cap — the static default)."""
    model, x = _serving_model()
    duration = 0.6 if quick else 2.0
    windows = (1.0, 2.0) if quick else (0.5, 1.0, 2.0, 4.0)
    return {
        str(w): _closed_loop_rate(model, x, 1024, w, duration)
        for w in windows
    }


def measure_serving_scale_up_backlog(quick: bool = False
                                     ) -> Dict[str, float]:
    """Time-to-recovery per scale-up backlog threshold: a 1-replica
    pool takes a closed-loop load spike it cannot absorb, a
    PoolAutoscaler with the candidate threshold closes the loop, and
    the measurement is how fast the pool's backlog EWMA falls back
    under the FIXED recovery criterion (0.4 — just below the lowest
    level every candidate's spike must decisively exceed, identical for
    every candidate so they compare; the closed-loop in-flight row
    count over the SCALED capacity is what recovery converges to, so
    the criterion sits above that floor, not at idle). Committed as
    1/recovery_s: higher-is-better keeps :func:`settle`'s hysteresis
    rule uniform across knobs. A lower threshold reacts earlier but
    sits closer to noise (flap risk the decisive-margin band absorbs);
    the measurement decides where this mesh's sweet spot is."""
    import threading

    from flinkml_tpu.serving import (
        AutoscaleConfig,
        PoolAutoscaler,
        ReplicaPool,
        ServingConfig,
    )
    from flinkml_tpu.table import Table

    model, x = _serving_model()
    thresholds = (0.25, 0.5) if quick else (0.25, 0.5, 0.75)
    timeout_s = 4.0 if quick else 10.0
    out: Dict[str, float] = {}
    for i, thr in enumerate(thresholds):
        pool = ReplicaPool(
            model, Table({"features": x[:4], "label": np.zeros(4)}),
            config=ServingConfig(max_batch_rows=64, max_queue_rows=256,
                                 max_wait_ms=1.0),
            n_replicas=1, output_cols=("prediction",),
            name=f"autotune-scale-{i}",
        ).start()
        scaler = PoolAutoscaler(pool, AutoscaleConfig(
            min_replicas=1, max_replicas=3, scale_up_backlog=thr,
            up_consecutive=2, down_consecutive=10_000,
            cooldown_s=0.2, interval_s=0.05, backlog_alpha=0.5,
        ))
        stop = threading.Event()

        def client(tid: int) -> None:
            rng = np.random.default_rng(7 + tid)  # Generators aren't
            while not stop.is_set():              # thread-safe: one each
                rows = int(rng.integers(24, 49))
                try:
                    pool.predict({"features": x[:rows],
                                  "label": np.zeros(rows)})
                except Exception:  # noqa: BLE001 — overload: keep offering
                    continue

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(6)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        recovery = timeout_s  # worst case: never recovered in budget
        spiked = False
        while time.perf_counter() - t0 < timeout_s:
            scaler.step()
            ewma = scaler._backlog_ewma or 0.0
            if not spiked:
                spiked = ewma > 0.85  # above every candidate's band
            elif ewma < 0.4:
                recovery = time.perf_counter() - t0
                break
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        pool.stop(drain=False)
        if not spiked:
            # The load generator never saturated this candidate's pool
            # on this host: the worst-case score below is a
            # measurement ARTIFACT, not a recovery result — say so, or
            # a committed winner could be chosen by load-generation
            # noise.
            _log.warning(
                "autotune: serving_scale_up_backlog candidate %s never "
                "saw its load spike (EWMA stayed under 0.85) — scoring "
                "worst-case %.1fs; treat this mesh's entry with "
                "suspicion", thr, timeout_s,
            )
        out[str(thr)] = 1.0 / max(recovery, 1e-3)
    return out


def measure_int8_min_const_elems(quick: bool = False) -> Dict[str, float]:
    """Fused-chain transform rows/s under the int8 tier per
    minimum-quantizable-constant-size threshold (driven through the
    ``FLINKML_TPU_INT8_MIN_CONST`` env gate so the search measures the
    exact product path). Small thresholds quantize every vector
    (maximum transfer savings, extra dequant ops); large ones leave
    small constants at float width."""
    from flinkml_tpu import pipeline_fusion
    from flinkml_tpu.table import Table

    model, x = _serving_model()
    table = Table({"features": x, "label": np.zeros(len(x))})
    reps = 3 if quick else 10
    thresholds = (8, 64) if quick else (4, 16, 64, 256)
    out: Dict[str, float] = {}
    for thr in thresholds:
        with _env("FLINKML_TPU_INT8_MIN_CONST", str(thr)):
            with pipeline_fusion.precision_scope("int8_inference"):
                np.asarray(  # warmup: compile this threshold's program
                    model.transform(table)[0].column("prediction")
                )

                def rate() -> float:
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        out_t = model.transform(table)[0]
                        np.asarray(out_t.column("prediction"))
                    return len(x) * reps / (time.perf_counter() - t0)

                out[str(thr)] = _timed_rate(rate)
    return out


# -- the kernel-backend family (flinkml_tpu.kernels) -------------------------
#
# Each site's A/B is driven through the FLINKML_TPU_KERNELS env gate so
# the search measures exactly the code path a user selecting that
# backend would run (the layout-knob discipline above). On a CPU mesh
# the Pallas candidate runs under the interpreter — expect XLA to keep
# winning there (the committed candidates make that auditable); the
# device re-tune is the measurement that can flip a default.


def measure_kernel_backend_fused_chain(quick: bool = False
                                       ) -> Dict[str, float]:
    """Fused 5-stage chain transform rows/s per chain backend (the
    product ``PipelineModel.transform`` path, both backends through the
    real fused-executor gate + cache)."""
    from flinkml_tpu import pipeline_fusion
    from flinkml_tpu.table import Table

    model, x = _serving_model()
    rows = min(1_024 if quick else 4_096, x.shape[0])
    reps = 3 if quick else 10
    batch = Table({"features": x[:rows], "label": np.zeros(rows)})
    out: Dict[str, float] = {}
    for backend in ("xla", "pallas"):
        with _env("FLINKML_TPU_KERNELS", f"fused_chain={backend}"):
            pipeline_fusion.reset_cache()
            (warm,) = model.transform(batch)
            read = [c for c in warm.column_names
                    if c not in ("features", "label")]
            for c in read:
                warm.column(c)

            def rate() -> float:
                t0 = time.perf_counter()
                for _ in range(reps):
                    (o,) = model.transform(batch)
                    for c in read:
                        o.column(c)
                return rows * reps / (time.perf_counter() - t0)

            out[backend] = _timed_rate(rate)
    pipeline_fusion.reset_cache()
    return out


def measure_kernel_backend_segment_sum(quick: bool = False
                                       ) -> Dict[str, float]:
    """Gradient-scatter cells/s per segment-sum backend at the sparse
    trainer's per-step shape (flat padded-ELL contributions into a
    dense [dim] gradient)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu import kernels

    cells, dim = (1 << 13, 1 << 14) if quick else (1 << 15, 1 << 16)
    reps = 5 if quick else 20
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, dim, cells), jnp.int32)
    vals = jnp.asarray(rng.normal(size=cells).astype(np.float32))
    out: Dict[str, float] = {}
    for backend in ("xla", "pallas"):
        fn = jax.jit(functools.partial(
            kernels.segment_sum, num_segments=dim, backend=backend,
        ))
        np.asarray(fn(vals, ids))  # compile + warmup

        def rate() -> float:
            t0 = time.perf_counter()
            for _ in range(reps):
                r = fn(vals, ids)
            np.asarray(r)
            return cells * reps / (time.perf_counter() - t0)

        out[backend] = _timed_rate(rate)
    return out


def measure_kernel_backend_spmv(quick: bool = False) -> Dict[str, float]:
    """Sparse forward-margin rows/s per SpMV backend at the sparse
    trainer's per-step shape (padded-ELL ``[rows, width]`` block
    against a dense ``[dim]`` coefficient)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu import kernels

    rows, width, dim = (1 << 11, 16, 1 << 14) if quick \
        else (1 << 13, 32, 1 << 16)
    reps = 5 if quick else 20
    rng = np.random.default_rng(0)
    ib = jnp.asarray(rng.integers(0, dim, (rows, width)), jnp.int32)
    vb = jnp.asarray(rng.normal(size=(rows, width)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=dim).astype(np.float32))
    out: Dict[str, float] = {}
    for backend in ("xla", "pallas"):
        fn = jax.jit(functools.partial(kernels.spmv, backend=backend))
        np.asarray(fn(ib, vb, w))  # compile + warmup

        def rate() -> float:
            t0 = time.perf_counter()
            for _ in range(reps):
                r = fn(ib, vb, w)
            np.asarray(r)
            return rows * reps / (time.perf_counter() - t0)

        out[backend] = _timed_rate(rate)
    return out


def measure_kernel_backend_topk(quick: bool = False) -> Dict[str, float]:
    """KNN-shaped queries/s per top-k backend (``[nq, n]`` distance
    matrix, k of the bench's neighbor-query size)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu import kernels

    nq, n, k = (256, 2_048, 8) if quick else (1_024, 8_192, 16)
    reps = 5 if quick else 20
    rng = np.random.default_rng(0)
    d2 = jnp.asarray(rng.normal(size=(nq, n)).astype(np.float32))
    out: Dict[str, float] = {}
    for backend in ("xla", "pallas"):
        fn = jax.jit(functools.partial(kernels.top_k, k=k, backend=backend))
        np.asarray(fn(-d2)[1])  # compile + warmup

        def rate() -> float:
            t0 = time.perf_counter()
            for _ in range(reps):
                _, idx = fn(-d2)
            np.asarray(idx)
            return nq * reps / (time.perf_counter() - t0)

        out[backend] = _timed_rate(rate)
    return out


def measure_embedding_exchange(quick: bool = False) -> Dict[str, float]:
    """Lookup+update rows/s per embedding-exchange candidate on a
    mid-size sharded table (one scatter-exchange + one lookup per
    measured step — the SGNS/table-update shape). ``ring`` and
    ``all_to_all`` run the real sharded exchange over the
    EMBEDDING-shaped mesh; ``dense_psum`` runs the below-threshold
    placement's real cost — a replicated table with one vocab-sized
    gradient psum per step over the data mesh — so the committed
    candidates show exactly where the dense path stops paying (the
    number behind the subsumed W2V threshold)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from flinkml_tpu.embeddings import EmbeddingTable
    from flinkml_tpu.parallel import DeviceMesh
    from flinkml_tpu.sharding import EMBEDDING

    vocab, dim, batch = ((1 << 13, 16, 1 << 11) if quick
                         else (1 << 17, 32, 1 << 13))
    reps = 3 if quick else 10
    rng = np.random.default_rng(0)
    rows0 = rng.normal(size=(vocab, dim)).astype(np.float32)
    ids = rng.integers(0, vocab, batch).astype(np.int32)
    delta = (rng.normal(size=(batch, dim)) * 1e-3).astype(np.float32)
    out: Dict[str, float] = {}

    mesh = DeviceMesh.for_plan(EMBEDDING)
    for strategy in ("ring", "all_to_all"):
        table = EmbeddingTable("tune", vocab, dim, mesh=mesh,
                               plan=EMBEDDING, rows=rows0)
        table.scatter_add(ids, delta, strategy=strategy)   # compile
        np.asarray(table.lookup(ids))

        def rate() -> float:
            t0 = time.perf_counter()
            for _ in range(reps):
                table.scatter_add(ids, delta, strategy=strategy)
                np.asarray(table.lookup(ids))
            return batch * reps / (time.perf_counter() - t0)

        out[strategy] = _timed_rate(rate)

    dmesh = DeviceMesh()
    p = dmesh.axis_size()
    axis = DeviceMesh.DATA_AXIS

    def dense_local(table, ids_l, delta_l):
        upd = jnp.zeros_like(table).at[ids_l].add(delta_l)
        return table + jax.lax.psum(upd, axis)

    dense_step = jax.jit(jax.shard_map(
        dense_local, mesh=dmesh.mesh,
        in_specs=(P(), P(axis), P(axis)), out_specs=P(),
    ))
    dense_lookup = jax.jit(lambda table, i: table[i])
    pad = (-batch) % p
    ids_p = np.concatenate([ids, np.zeros(pad, np.int32)])
    delta_p = np.concatenate(
        [delta, np.zeros((pad, dim), np.float32)]
    )
    rows_dev = jnp.asarray(rows0)
    rows_dev = dense_step(rows_dev, dmesh.shard_batch(ids_p),
                          dmesh.shard_batch(delta_p))   # compile
    np.asarray(dense_lookup(rows_dev, ids))

    def dense_rate() -> float:
        nonlocal rows_dev
        t0 = time.perf_counter()
        for _ in range(reps):
            rows_dev = dense_step(rows_dev, dmesh.shard_batch(ids_p),
                                  dmesh.shard_batch(delta_p))
            np.asarray(dense_lookup(rows_dev, ids))
        return batch * reps / (time.perf_counter() - t0)

    out["dense_psum"] = _timed_rate(dense_rate)
    return out


# -- the search harness ------------------------------------------------------

MEASURERS: Dict[str, Callable[[bool], Dict[str, float]]] = {
    "sparse_layout": measure_sparse_layout,
    "gbt_histogram": measure_gbt_histogram,
    "als_reduction": measure_als_reduction,
    "w2v_accum": measure_w2v_accum,
    "infer_plan_order": measure_infer_plan_order,
    "serving_max_batch_rows": measure_serving_max_batch_rows,
    "serving_window_ms": measure_serving_window_ms,
    "kernel_backend_fused_chain": measure_kernel_backend_fused_chain,
    "kernel_backend_segment_sum": measure_kernel_backend_segment_sum,
    "kernel_backend_spmv": measure_kernel_backend_spmv,
    "kernel_backend_topk": measure_kernel_backend_topk,
    "embedding_exchange": measure_embedding_exchange,
    "serving_scale_up_backlog": measure_serving_scale_up_backlog,
    "int8_min_const_elems": measure_int8_min_const_elems,
}


def search_knobs(knobs: Optional[Sequence[str]] = None, *,
                 quick: bool = False,
                 source: str = "flinkml_tpu.autotune") -> Dict[str, dict]:
    """Measure ``knobs`` (default: all) and settle each winner — the
    seat-holder being the currently COMMITTED table value for this mesh
    when one exists (see :func:`settle`). Returns
    ``{knob: {"value", "unit", "candidates"}}`` ready for
    :meth:`TuningTable.set_knob`."""
    from flinkml_tpu.autotune.table import load_table

    try:
        committed_mesh = mesh_key()
    except Exception:  # noqa: BLE001 — no backend: static incumbents
        committed_mesh = None
    table = load_table()
    results: Dict[str, dict] = {}
    for knob in (knobs or list(MEASURERS)):
        if knob not in MEASURERS:
            raise ValueError(
                f"unknown knob {knob!r}; known: {sorted(MEASURERS)}"
            )
        _log.info("autotune: measuring %s ...", knob)
        t0 = time.perf_counter()
        candidates = MEASURERS[knob](quick)
        if knob == "infer_plan_order":
            value: Any = order_presets(candidates)
        else:
            committed = (table.value(committed_mesh, knob)
                         if committed_mesh else None)
            value = settle(knob, candidates, incumbent=committed)
        _log.info(
            "autotune: %s -> %r in %.1fs (candidates: %s)", knob, value,
            time.perf_counter() - t0,
            {k: round(v, 1) for k, v in candidates.items()},
        )
        results[knob] = {
            "value": value,
            "unit": KNOWN_KNOBS[knob],
            "candidates": {k: round(float(v), 2)
                           for k, v in candidates.items()},
        }
    return results


def apply_results(table: TuningTable, results: Dict[str, dict], *,
                  mesh: Optional[str] = None,
                  source: str = "flinkml_tpu.autotune") -> TuningTable:
    mesh = mesh or mesh_key()
    for knob, rec in results.items():
        table.set_knob(
            mesh, knob, rec["value"], candidates=rec["candidates"],
            unit=rec["unit"], source=source,
        )
    return table
