"""The mesh-keyed tuning table.

Format (``tuning_table.json``, committed next to this module)::

    {
      "version": 1,
      "entries": {
        "<backend>/<device_kind>/<n_devices>": {
          "<knob>": {
            "value": <winner>,
            "unit": "<what the candidates were measured in>",
            "candidates": {"<candidate>": <measured value>, ...},
            "measured_at": "<UTC ISO stamp>",
            "source": "<harness that measured it>"
          }, ...
        }, ...
      }
    }

The mesh key is the measurement's validity domain: a winner measured on
an 8-virtual-device CPU mesh says nothing about a v5p pod, so lookups
only ever see their own mesh's entry (the device re-tune lands as a new
entry when the tunnel returns — ``bench.py``'s ``autotune`` stage).

``candidates`` is committed alongside the winner on purpose: a reader
can see HOW decisive the win was, and the search's hysteresis rule
(flip the default only on a >1.10x win, so measurement noise never
flip-flops a committed default) is auditable after the fact.

Lookup precedence at every consulted site: explicit env var / argument
> tuning-table entry for the current mesh > static fallback.
``FLINKML_TPU_AUTOTUNE=0`` turns the middle layer off.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("autotune")

#: The committed table (package data).
DEFAULT_TABLE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tuning_table.json"
)

#: Point lookups at a different table file.
ENV_TABLE_VAR = "FLINKML_TPU_TUNING_TABLE"

#: ``=0`` disables every table consult (static defaults only).
ENV_DISABLE_VAR = "FLINKML_TPU_AUTOTUNE"

#: Every knob a table may carry, with the unit its candidates are
#: measured in — ``--check`` refuses unknown knobs so a typo'd entry
#: cannot sit silently unconsulted.
KNOWN_KNOBS: Dict[str, str] = {
    "sparse_layout": "samples_per_sec",
    "gbt_histogram": "row_trees_per_sec",
    "als_reduction": "rating_visits_per_sec",
    "w2v_accum": "pairs_per_sec",
    "infer_plan_order": "samples_per_sec",
    "serving_max_batch_rows": "rows_per_sec",
    "serving_window_ms": "rows_per_sec",
    # The kernel-backend family (flinkml_tpu.kernels): xla vs pallas
    # per gated site. Committed CPU entries measure the INTERPRETER
    # (auditable, not competitive); the device re-tune (bench stage
    # `pallas`) is what can flip these.
    "kernel_backend_fused_chain": "rows_per_sec",
    "kernel_backend_segment_sum": "cells_per_sec",
    "kernel_backend_spmv": "rows_per_sec",
    "kernel_backend_topk": "queries_per_sec",
    # The sharded-embedding exchange (flinkml_tpu.embeddings): ring vs
    # all_to_all row routing, with dense_psum (replicated table, dense
    # gradient psum) as the below-threshold candidate — the knob that
    # subsumed W2V's static _shard_vocab_threshold.
    "embedding_exchange": "lookup_update_rows_per_sec",
    # The autoscaler's scale-up backlog threshold (queued rows as a
    # fraction of per-replica queue capacity): candidates measured by
    # the wall-clock time for the pool's backlog EWMA to recover under
    # a closed-loop load triple — lower is better, so the committed
    # candidates store 1/recovery_s (higher-is-better keeps the
    # settle() hysteresis rule uniform across knobs).
    "serving_scale_up_backlog": "inverse_recovery_s",
    # The int8 tier's minimum constant size worth quantizing (elements):
    # below it, per-column scales + dequant overhead outweigh the
    # bandwidth saved on tiny vectors.
    "int8_min_const_elems": "rows_per_sec",
}

_CACHE_LOCK = threading.Lock()
_CACHE: Dict[str, Tuple[float, "TuningTable"]] = {}
_WARNED: set = set()


def mesh_key(backend: Optional[str] = None,
             device_kind: Optional[str] = None,
             n_devices: Optional[int] = None) -> str:
    """The current (or given) mesh's table key:
    ``backend/device_kind/n_devices`` with the device kind sanitized
    (``TPU v4`` → ``TPU_v4``)."""
    if backend is None or device_kind is None or n_devices is None:
        import jax

        devs = jax.devices()
        backend = backend or jax.default_backend()
        device_kind = device_kind or devs[0].device_kind
        n_devices = n_devices if n_devices is not None else len(devs)
    kind = re.sub(r"[^A-Za-z0-9_.-]", "_", str(device_kind))
    return f"{backend}/{kind}/{int(n_devices)}"


class TuningTable:
    """In-memory view of one table file (see module docstring)."""

    def __init__(self, data: Optional[dict] = None,
                 path: Optional[str] = None):
        self.data = data or {"version": 1, "entries": {}}
        self.path = path

    # -- lookups -----------------------------------------------------------
    def record(self, mesh: str, knob: str) -> Optional[dict]:
        return self.data.get("entries", {}).get(mesh, {}).get(knob)

    def value(self, mesh: str, knob: str) -> Any:
        rec = self.record(mesh, knob)
        return None if rec is None else rec.get("value")

    def meshes(self) -> Tuple[str, ...]:
        return tuple(self.data.get("entries", {}))

    # -- mutation ----------------------------------------------------------
    def set_knob(self, mesh: str, knob: str, value: Any, *,
                 candidates: Optional[Dict[str, float]] = None,
                 unit: Optional[str] = None,
                 measured_at: Optional[str] = None,
                 source: str = "flinkml_tpu.autotune") -> None:
        if knob not in KNOWN_KNOBS:
            raise ValueError(
                f"unknown tuning knob {knob!r}; known: "
                f"{sorted(KNOWN_KNOBS)}"
            )
        if measured_at is None:
            import datetime

            measured_at = (
                datetime.datetime.now(datetime.timezone.utc)
                .strftime("%Y-%m-%dT%H:%M:%SZ")
            )
        entry = self.data.setdefault("entries", {}).setdefault(mesh, {})
        entry[knob] = {
            "value": value,
            "unit": unit or KNOWN_KNOBS[knob],
            "candidates": dict(candidates or {}),
            "measured_at": measured_at,
            "source": source,
        }

    def save(self, path: Optional[str] = None) -> str:
        """Atomic write (temp file + rename — a reader never sees a torn
        table)."""
        path = path or self.path or DEFAULT_TABLE_PATH
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-tune-")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(self.data, fh, indent=2, sort_keys=True)
                fh.write("\n")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    # -- validation --------------------------------------------------------
    def check(self) -> Sequence[str]:
        """Schema problems, empty when clean (the CI gate)."""
        problems = []
        if self.data.get("version") != 1:
            problems.append(f"version != 1: {self.data.get('version')!r}")
        entries = self.data.get("entries")
        if not isinstance(entries, dict):
            return problems + ["entries is not a dict"]
        for mesh, knobs in entries.items():
            if not re.fullmatch(r"[^/]+/[^/]+/\d+", mesh):
                problems.append(f"bad mesh key {mesh!r}")
            if not isinstance(knobs, dict):
                problems.append(f"{mesh}: knobs is not a dict")
                continue
            for knob, rec in knobs.items():
                where = f"{mesh}/{knob}"
                if knob not in KNOWN_KNOBS:
                    problems.append(f"{where}: unknown knob")
                    continue
                if not isinstance(rec, dict) or "value" not in rec:
                    problems.append(f"{where}: record has no value")
                    continue
                for field in ("candidates", "measured_at", "source", "unit"):
                    if field not in rec:
                        problems.append(f"{where}: missing {field!r}")
                cands = rec.get("candidates")
                if not isinstance(cands, dict) or not cands:
                    problems.append(
                        f"{where}: no measured candidates — a committed "
                        "value must be measured, not guessed"
                    )
        return problems


def load_table(path: Optional[str] = None) -> TuningTable:
    """The table at ``path`` (default: ``$FLINKML_TPU_TUNING_TABLE`` or
    the committed one), cached by mtime. A missing file is an empty
    table; an unparsable one logs loudly and acts empty (a bad table
    must never take training down)."""
    path = path or os.environ.get(ENV_TABLE_VAR) or DEFAULT_TABLE_PATH
    path = os.path.abspath(path)
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return TuningTable(path=path)
    with _CACHE_LOCK:
        cached = _CACHE.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
    try:
        with open(path) as fh:
            table = TuningTable(json.load(fh), path=path)
    except Exception as e:  # noqa: BLE001 — a bad table is an empty table
        if path not in _WARNED:
            _WARNED.add(path)
            _log.warning(
                "tuning table %s is unreadable (%s: %s); using static "
                "defaults", path, type(e).__name__, e,
            )
        return TuningTable(path=path)
    with _CACHE_LOCK:
        _CACHE[path] = (mtime, table)
    return table


def tuned_default(knob: str, fallback: Any,
                  allowed: Optional[Sequence[Any]] = None,
                  mesh: Optional[str] = None) -> Any:
    """The measured default for ``knob`` on the current mesh, or
    ``fallback`` when autotuning is disabled, the mesh has no entry, or
    the entry's value fails ``allowed`` (logged once — a stale table
    must degrade, not crash)."""
    if os.environ.get(ENV_DISABLE_VAR) == "0":
        return fallback
    try:
        mesh = mesh or mesh_key()
    except Exception:  # noqa: BLE001 — no backend yet: static default
        return fallback
    value = load_table().value(mesh, knob)
    if value is None:
        return fallback
    if allowed is not None and value not in allowed:
        tag = (knob, mesh)
        if tag not in _WARNED:
            _WARNED.add(tag)
            _log.warning(
                "tuning table value %r for knob %s (mesh %s) is not one "
                "of %s; using the static default %r",
                value, knob, mesh, list(allowed), fallback,
            )
        return fallback
    return value
