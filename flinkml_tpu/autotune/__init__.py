"""Measurement-driven autotuning — guessed defaults become measured ones.

The heuristics that pick the executor's cache identities are guesses:
``infer_plan``'s ascending-communication-cost preset order, the serving
engine's power-of-two dispatch bucket cap and batching window, and the
four sort-class layout gates (sparse-LR ``FLINKML_TPU_SPARSE_LAYOUT``,
GBT ``FLINKML_TPU_GBT_HISTOGRAM``, ALS ``FLINKML_TPU_ALS_REDUCTION``,
W2V ``FLINKML_TPU_W2V_ACCUM``) that have been "flip on a measured win"
since they landed. This package measures them
(:mod:`flinkml_tpu.autotune.search`) and pins winners into a committed,
mesh-keyed tuning table (:mod:`flinkml_tpu.autotune.table`) consulted at
key-construction time: an explicit env var or argument always wins, the
table supplies the default, and the static fallback only fires when the
current mesh has no measured entry.

Run the search::

    python -m flinkml_tpu.autotune --quick          # measure + print
    python -m flinkml_tpu.autotune --commit          # rewrite the table
    python -m flinkml_tpu.autotune --check           # CI schema gate

``FLINKML_TPU_AUTOTUNE=0`` disables every table consult (pure static
defaults — the escape hatch). See
``docs/development/compile_cache.md`` for the table format and runbook.
"""

from flinkml_tpu.autotune.table import (  # noqa: F401
    DEFAULT_TABLE_PATH,
    KNOWN_KNOBS,
    TuningTable,
    load_table,
    mesh_key,
    tuned_default,
)

__all__ = [
    "DEFAULT_TABLE_PATH",
    "KNOWN_KNOBS",
    "TuningTable",
    "load_table",
    "mesh_key",
    "tuned_default",
]
