"""``python -m flinkml_tpu.autotune`` — run the knob search, check or
rewrite the committed tuning table.

Modes:

- default (no flags): measure and PRINT the results as JSON, leaving
  the table untouched (a dry run);
- ``--commit``: measure and rewrite the table's entry for the current
  mesh (atomic; other meshes' entries are preserved);
- ``--check``: validate the table's schema without measuring anything —
  the CI gate (exit 1 on any problem).

``--quick`` shrinks every scenario to smoke size; committed values
should come from a full run on an otherwise-idle machine.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m flinkml_tpu.autotune",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--knobs", default=None,
        help="comma-separated knob subset (default: all)",
    )
    parser.add_argument("--quick", action="store_true",
                        help="smoke-size scenarios")
    parser.add_argument("--commit", action="store_true",
                        help="rewrite the tuning table")
    parser.add_argument("--table", default=None,
                        help="table path (default: the committed one)")
    parser.add_argument("--mesh", default=None,
                        help="override the mesh key to write under")
    parser.add_argument("--source", default="python -m flinkml_tpu.autotune",
                        help="provenance string recorded per knob")
    parser.add_argument("--check", action="store_true",
                        help="validate the table schema and exit")
    args = parser.parse_args(argv)

    from flinkml_tpu.autotune.table import load_table

    if args.check:
        table = load_table(args.table)
        problems = list(table.check())
        for p in problems:
            print(f"tuning-table problem: {p}", file=sys.stderr)
        if not problems:
            print(f"tuning table OK: {table.path} "
                  f"({len(table.meshes())} mesh entries)")
        return 1 if problems else 0

    from flinkml_tpu.autotune.search import apply_results, search_knobs

    knobs = args.knobs.split(",") if args.knobs else None
    results = search_knobs(knobs, quick=args.quick, source=args.source)
    print(json.dumps(results, indent=2, sort_keys=True))
    if args.commit:
        table = load_table(args.table)
        apply_results(table, results, mesh=args.mesh, source=args.source)
        path = table.save(args.table)
        print(f"tuning table updated: {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
