"""Shared ``HasXxx`` parameter mixins.

Parity: the 17 mixin interfaces in
``flink-ml-lib/.../ml/common/param/Has*.java`` (SURVEY.md §2.3) — same param
names, defaults, and validators. Stages compose these by inheritance exactly
as the reference's interfaces compose by ``extends``.
"""

from __future__ import annotations

from flinkml_tpu.params import (
    FloatParam,
    IntParam,
    LongParam,
    ParamValidators,
    StringArrayParam,
    StringParam,
    WithParams,
)


class HasFeaturesCol(WithParams):
    FEATURES_COL = StringParam(
        "featuresCol", "Features column name.", "features", ParamValidators.not_null()
    )


class HasLabelCol(WithParams):
    LABEL_COL = StringParam(
        "labelCol", "Label column name.", "label", ParamValidators.not_null()
    )


class HasPredictionCol(WithParams):
    PREDICTION_COL = StringParam(
        "predictionCol", "Prediction column name.", "prediction", ParamValidators.not_null()
    )


class HasRawPredictionCol(WithParams):
    RAW_PREDICTION_COL = StringParam(
        "rawPredictionCol", "Raw prediction column name.", "rawPrediction"
    )


class HasWeightCol(WithParams):
    WEIGHT_COL = StringParam("weightCol", "Weight column name.", None)


class HasMaxIter(WithParams):
    MAX_ITER = IntParam(
        "maxIter", "Maximum number of iterations.", 20, ParamValidators.gt(0)
    )


class HasReg(WithParams):
    REG = FloatParam("reg", "Regularization parameter.", 0.0, ParamValidators.gt_eq(0.0))


class HasLearningRate(WithParams):
    LEARNING_RATE = FloatParam(
        "learningRate", "Learning rate of optimization method.", 0.1,
        ParamValidators.gt(0.0),
    )


class HasGlobalBatchSize(WithParams):
    GLOBAL_BATCH_SIZE = IntParam(
        "globalBatchSize", "Global batch size of training algorithms.", 32,
        ParamValidators.gt(0),
    )


class HasTol(WithParams):
    TOL = FloatParam(
        "tol", "Convergence tolerance for iterative algorithms.", 1e-6,
        ParamValidators.gt_eq(0.0),
    )


class HasSeed(WithParams):
    SEED = LongParam("seed", "The random seed.", None)

    def get_seed(self) -> int:
        """Default seed is drawn once per call when unset (reference:
        HasSeed.getSeed falls back to a random value)."""
        seed = self.get(HasSeed.SEED)
        if seed is None:
            import random

            return random.getrandbits(31)
        return int(seed)


class HasMultiClass(WithParams):
    MULTI_CLASS = StringParam(
        "multiClass", "Classification type.", "auto",
        ParamValidators.in_array(["auto", "binomial", "multinomial"]),
    )


class HasSmoothing(WithParams):
    SMOOTHING = FloatParam(
        "smoothing", "The smoothing parameter.", 1.0, ParamValidators.gt_eq(0.0)
    )


class HasK(WithParams):
    K = IntParam(
        "k", "The number of nearest neighbors.", 5, ParamValidators.gt(0)
    )


class HasDistanceMeasure(WithParams):
    DISTANCE_MEASURE = StringParam(
        "distanceMeasure", "Distance measure.", "euclidean",
        ParamValidators.in_array(["euclidean", "cosine", "manhattan"]),
    )


class HasInputCol(WithParams):
    INPUT_COL = StringParam("inputCol", "Input column name.", "input")


class HasOutputCol(WithParams):
    OUTPUT_COL = StringParam("outputCol", "Output column name.", "output")


class HasInputCols(WithParams):
    INPUT_COLS = StringArrayParam(
        "inputCols", "Input column names.", None, ParamValidators.non_empty_array()
    )


class HasOutputCols(WithParams):
    OUTPUT_COLS = StringArrayParam(
        "outputCols", "Output column names.", None, ParamValidators.non_empty_array()
    )


class HasHandleInvalid(WithParams):
    ERROR_INVALID = "error"
    SKIP_INVALID = "skip"
    KEEP_INVALID = "keep"

    HANDLE_INVALID = StringParam(
        "handleInvalid", "Strategy to handle invalid entries.", "error",
        ParamValidators.in_array(["error", "skip", "keep"]),
    )


class HasBatchStrategy(WithParams):
    """Online-algorithm batching strategy (reference: HasBatchStrategy with
    COUNT strategy only)."""

    COUNT_STRATEGY = "count"

    BATCH_STRATEGY = StringParam(
        "batchStrategy", "Strategy to create mini batch from online train data.",
        "count", ParamValidators.in_array(["count"]),
    )


class HasDecayFactor(WithParams):
    DECAY_FACTOR = FloatParam(
        "decayFactor", "The forgetfulness of the previous centroids.", 0.0,
        ParamValidators.in_range(0.0, 1.0),
    )


class HasElasticNet(WithParams):
    ELASTIC_NET = FloatParam(
        "elasticNet", "ElasticNet parameter (0 = L2, 1 = L1).", 0.0,
        ParamValidators.in_range(0.0, 1.0),
    )
