"""Distance measures.

Parity: ``ml/common/distance/DistanceMeasure.java:26-43`` — an SPI with a
named factory (``DistanceMeasure.getInstance("euclidean")``) and a single
``EuclideanDistanceMeasure`` implementation.

TPU-first: the per-pair ``distance(a, b)`` exists for API parity, but the
real interface is ``pairwise`` — a full [n, m] distance matrix in one MXU
matmul — and ``nearest``/argmin over it, which is what KMeans/KNN use.
"""

from __future__ import annotations

from typing import Dict, Type

import jax.numpy as jnp

from flinkml_tpu.ops import blas


class DistanceMeasure:
    """SPI for distance measures; instances are stateless."""

    NAME = "base"
    _registry: Dict[str, "DistanceMeasure"] = {}

    @classmethod
    def register(cls, impl_cls: Type["DistanceMeasure"]) -> Type["DistanceMeasure"]:
        cls._registry[impl_cls.NAME] = impl_cls()
        return impl_cls

    @staticmethod
    def get_instance(name: str) -> "DistanceMeasure":
        # Parity: DistanceMeasure.getInstance (DistanceMeasure.java:31-39).
        impl = DistanceMeasure._registry.get(name)
        if impl is None:
            raise ValueError(
                f"distanceMeasure must be one of {sorted(DistanceMeasure._registry)}, "
                f"got {name!r}"
            )
        return impl

    def distance(self, a, b):
        raise NotImplementedError

    def pairwise(self, xs, ys):
        """[n, d] x [m, d] -> [n, m] distances."""
        raise NotImplementedError

    def nearest(self, xs, centroids):
        """Index of nearest centroid per row: [n, d] x [k, d] -> [n] int32."""
        return jnp.argmin(self.pairwise(xs, centroids), axis=-1)


@DistanceMeasure.register
class EuclideanDistanceMeasure(DistanceMeasure):
    """Parity: ``EuclideanDistanceMeasure.java``."""

    NAME = "euclidean"

    def distance(self, a, b):
        return blas.norm2(jnp.asarray(a) - jnp.asarray(b))

    def pairwise(self, xs, ys):
        return jnp.sqrt(blas.squared_distances(xs, ys))

    def nearest(self, xs, centroids):
        # argmin over squared distances avoids the sqrt entirely.
        return jnp.argmin(blas.squared_distances(xs, centroids), axis=-1)


@DistanceMeasure.register
class CosineDistanceMeasure(DistanceMeasure):
    """Cosine distance = 1 - cos(a, b); an addition beyond the reference's
    single measure, registered through the same SPI."""

    NAME = "cosine"

    def distance(self, a, b):
        a = jnp.asarray(a)
        b = jnp.asarray(b)
        return 1.0 - jnp.dot(a, b) / (blas.norm2(a) * blas.norm2(b))

    def pairwise(self, xs, ys):
        xs = jnp.asarray(xs)
        ys = jnp.asarray(ys)
        xn = xs / jnp.linalg.norm(xs, axis=-1, keepdims=True)
        yn = ys / jnp.linalg.norm(ys, axis=-1, keepdims=True)
        return 1.0 - xn @ yn.T


@DistanceMeasure.register
class ManhattanDistanceMeasure(DistanceMeasure):
    """L1 distance; addition beyond the reference."""

    NAME = "manhattan"

    def distance(self, a, b):
        return jnp.sum(jnp.abs(jnp.asarray(a) - jnp.asarray(b)))

    def pairwise(self, xs, ys):
        xs = jnp.asarray(xs)
        ys = jnp.asarray(ys)
        return jnp.sum(jnp.abs(xs[:, None, :] - ys[None, :, :]), axis=-1)
