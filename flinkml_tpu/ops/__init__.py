from flinkml_tpu.ops import blas
from flinkml_tpu.ops.distance import DistanceMeasure, EuclideanDistanceMeasure
from flinkml_tpu.ops.sparse import BatchedCSR

__all__ = ["blas", "DistanceMeasure", "EuclideanDistanceMeasure", "BatchedCSR"]
