"""Margin-loss math shared by every linear trainer.

The single source of ``d loss/d margin`` (and per-example loss) for the
linear-model family — the TPU counterpart of the reference's per-record
loss kernels (``LogisticGradient.java:50-96`` for logistic; hinge and
squared extend the family). Lives in its own module so every consumer
(dense stepper, sparse steppers, streamed stepper) uses identical math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def margin_terms(loss: str, dot, y, w):
    """(d loss/d margin, per-example loss), weighted.

    Labels ``y`` are {0, 1}; margin losses map them to ``ys = 2y - 1``.
    """
    if loss == "logistic":
        ys = 2.0 * y - 1.0
        margin = dot * ys
        mult = w * (-ys * jax.nn.sigmoid(-margin))
        per_ex = w * jax.nn.softplus(-margin)
    elif loss == "hinge":
        ys = 2.0 * y - 1.0
        margin = dot * ys
        active = (margin < 1.0).astype(dot.dtype)
        mult = w * (-ys * active)
        per_ex = w * jnp.maximum(0.0, 1.0 - margin)
    elif loss == "squared":
        resid = dot - y
        mult = w * resid
        per_ex = 0.5 * w * resid * resid
    else:  # pragma: no cover - guarded by callers
        raise ValueError(f"unknown loss {loss!r}")
    return mult, per_ex
