"""Pallas TPU kernels for the framework's hot loops.

The reference's entire numeric kernel layer is a per-record JVM BLAS
(``flink-ml-core/.../linalg/BLAS.java:26-91`` driving
``LogisticGradient.java:50-96`` one dot/axpy per record). Here the hot
loops are batched XLA programs already; these Pallas kernels go one step
further and fuse each loop's full per-tile pipeline so the batch is read
from HBM exactly once:

  - ``fused_linear_grad``: forward margins (MXU), d-loss/d-margin (VPU),
    and the gradient back-product (MXU) in one pass over ``x``. The plain
    XLA lowering reads ``x`` twice (once for ``x @ coef``, once for
    ``x.T @ mult``); at a9a/Criteo batch sizes the loop is HBM-bound, so
    halving traffic on ``x`` is the headline win.
  - ``fused_kmeans_step``: pairwise distances (MXU), argmin, and one-hot
    accumulation of per-cluster sums/counts without ever materialising
    the ``[n, k]`` distance or assignment matrices in HBM.

Both kernels accumulate into their output blocks across a 1-D row-tile
grid (output index map is constant, initialised at ``program_id == 0``),
the canonical Pallas reduction pattern. Row counts must be multiples of
the tile; callers pad with zero-weight rows, which are exact no-ops in
every sum below.

On non-TPU backends the kernels run in interpreter mode, so the test
suite exercises the identical kernel code on the CPU mesh.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Measured per-kernel defaults for FLINKML_TPU_PALLAS=auto (BASELINE.md,
# "Kernel-path verdict (round 2)": both RETIRED from auto on evidence):
#   linear: OFF — on v5e XLA's two-pass lowering beats the fused kernel
#     at every measured shape (f32 d=123: 0.70x; bf16 d=123: 0.82x): the
#     [tile,d]x[d,1] matvec uses 1/128 of the MXU and Mosaic cannot
#     pipeline past it, regardless of precision or tile height.
#   kmeans: OFF — measured 0.39-0.72x vs XLA's argmin+one-hot-matmul
#     lowering across (d,k) in {64x16, 128x64, 256x256}.
# Both kernels stay correct + tested and reachable via
# FLINKML_TPU_PALLAS=always for future TPU/Mosaic generations.
_AUTO_DEFAULTS = {"linear": False, "kmeans": False}


def pallas_active(kernel: str = "linear") -> bool:
    """Whether the fused kernel named ``kernel`` replaces its plain-XLA
    hot loop.

    ``FLINKML_TPU_PALLAS``: ``auto`` (default — per-kernel measured
    defaults above), ``always`` (any backend; interpret mode off-TPU —
    how the test suite exercises kernel code on the CPU mesh), or
    ``never``.
    """
    if kernel not in _AUTO_DEFAULTS:
        raise KeyError(
            f"unknown kernel {kernel!r}; add a measured default to "
            f"_AUTO_DEFAULTS (known: {sorted(_AUTO_DEFAULTS)})"
        )
    mode = os.environ.get("FLINKML_TPU_PALLAS", "auto").lower()
    if mode == "always":
        return True
    if mode == "never":
        return False
    return _AUTO_DEFAULTS[kernel]


def pallas_enabled(n_rows: int, kernel: str = "linear") -> bool:
    """``pallas_active(kernel)`` plus the shape requirement: rows must be
    a multiple of the minimum (f32 sublane) tile. The kernel-name check
    runs first so typos fail loudly regardless of the batch shape."""
    return pallas_active(kernel) and n_rows % 8 == 0

# Row-tile heights to try, best first. All multiples of the f32 sublane
# tile (8); the largest divisor of the batch is picked so the grid is
# exact and no masking is needed.
_TILES = (512, 256, 128, 64, 32, 16, 8)


def _pick_tile(n: int) -> int:
    for t in _TILES:
        if n % t == 0:
            return t
    raise ValueError(
        f"row count {n} is not a multiple of 8; pad with zero-weight rows"
    )


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Fused linear-model gradient
# ---------------------------------------------------------------------------

# The single source of the margin math is ``ops.losses.margin_terms``;
# the fused kernels and every unfused stepper share it so the paths
# cannot drift.
from flinkml_tpu.ops.losses import margin_terms as _margin_terms  # noqa: E402


def _linear_grad_kernel(loss: str, acc_dt, x_ref, y_ref, w_ref, coef_ref,
                        grad_ref, stats_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        grad_ref[:] = jnp.zeros_like(grad_ref)
        stats_ref[0, 0] = jnp.zeros((), stats_ref.dtype)  # SMEM: scalar stores
        stats_ref[0, 1] = jnp.zeros((), stats_ref.dtype)

    # Mosaic wants strictly 2-D matmuls: margins/labels ride as [T, 1]
    # column vectors, contractions are expressed via dot_general so no
    # transpose relayout is ever emitted. Sub-f32 inputs are bf16 in HBM
    # (halved traffic — the point of the fused pass) but compute in f32
    # (``acc_dt``) after the VMEM load: the d→1 matvec lowers to VPU
    # broadcast-reduce, Mosaic cannot lower transcendentals
    # (logistic/softplus) or mixed-dtype contractions on bf16 vectors,
    # and bf16 accumulation would lose the sums anyway.
    x = x_ref[:].astype(acc_dt)        # [T, d]
    dot = jax.lax.dot_general(         # x [T,d] . coef [1,d] -> [T,1]
        x, coef_ref[:].astype(acc_dt), (((1,), (1,)), ((), ())),
        preferred_element_type=acc_dt,
        precision=jax.lax.Precision.HIGHEST,
    )
    mult, per_ex = _margin_terms(
        loss, dot, y_ref[:].astype(acc_dt), w_ref[:].astype(acc_dt)
    )
    grad_ref[:] += jax.lax.dot_general(  # mult [T,1] . x [T,d] -> [1,d]
        mult, x, (((0,), (0,)), ((), ())),
        preferred_element_type=acc_dt,
        precision=jax.lax.Precision.HIGHEST,
    )
    stats_ref[0, 0] += jnp.sum(per_ex)
    stats_ref[0, 1] += jnp.sum(w_ref[:].astype(acc_dt))


@functools.partial(jax.jit, static_argnames=("loss", "interpret"))
def fused_linear_grad(x, y, w, coef, *, loss: str, interpret: bool = None):
    """One-pass gradient for a linear model batch.

    Args:
        x: [n, d] features, n a multiple of 8 (pad rows carry w = 0).
        y: [n] labels, w: [n] example weights, coef: [d] model.
    Returns:
        (grad [d], loss_sum scalar, weight_sum scalar) — for f32/f64
        inputs, identical math to the unfused ``x.T @ mult`` path, with
        ``x`` read from HBM once. Sub-f32 inputs (bf16) compute margins
        and accumulate in f32 and round the results back, so they differ
        from the all-bf16 unfused path by quantization (the fused result
        is the more accurate one).
    """
    if interpret is None:
        interpret = _interpret()
    n, d = x.shape
    tile = _pick_tile(n)
    grid = n // tile
    dt = x.dtype
    # Sub-f32 inputs accumulate (and run VPU math) in f32; wider dtypes
    # (f32, and f64 in interpreter tests) accumulate natively.
    acc_dt = jnp.float32 if jnp.dtype(dt).itemsize < 4 else dt
    kernel = functools.partial(_linear_grad_kernel, loss, acc_dt)
    grad, stats = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, d), acc_dt),
            jax.ShapeDtypeStruct((1, 2), acc_dt),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * n * d,
            bytes_accessed=(n * d + 3 * n) * jnp.dtype(dt).itemsize
            + 2 * d * jnp.dtype(acc_dt).itemsize,
            transcendentals=2 * n if loss == "logistic" else 0,
        ),
        interpret=interpret,
    )(x, y[:, None], w[:, None], coef[None, :])
    return grad[0].astype(dt), stats[0, 0].astype(dt), stats[0, 1].astype(dt)


# ---------------------------------------------------------------------------
# Fused KMeans Lloyd step
# ---------------------------------------------------------------------------

def _kmeans_kernel(x_ref, w_ref, cent_ref, cnorm_ref, sums_ref, counts_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    x = x_ref[:]                       # [T, d]
    c = cent_ref[:]                    # [k, d]
    # argmin_j |x - c_j|^2 == argmin_j (|c_j|^2 - 2 x.c_j); |x|^2 is constant
    # per row. Centroids arrive unpadded ([k, d] exactly); Mosaic handles
    # sub-tile k internally.
    scores = cnorm_ref[:] - 2.0 * jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=x.dtype,
        precision=jax.lax.Precision.HIGHEST
    )                                   # [T, k]
    k = scores.shape[1]
    best = jnp.min(scores, axis=1, keepdims=True)
    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    # One-hot of the (first) argmin, weighted; ties broken by lowest index.
    is_min = scores == best
    first = jnp.min(jnp.where(is_min, col, k), axis=1, keepdims=True)
    onehot = (col == first).astype(x.dtype) * w_ref[:]  # [T, k]
    sums_ref[:] += jax.lax.dot_general(  # onehot [T,k] . x [T,d] -> [k,d]
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=x.dtype,
        precision=jax.lax.Precision.HIGHEST
    )
    counts_ref[0, :] += jnp.sum(onehot, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_kmeans_step(x, w, centroids, *, interpret: bool = None):
    """One Lloyd accumulation pass: per-cluster weighted sums and counts.

    Args:
        x: [n, d] points, n a multiple of 8 (pad rows carry w = 0).
        w: [n] weights (0 masks a row out entirely).
        centroids: [k, d] current centroids.
    Returns:
        (sums [k, d], counts [k]); caller divides and handles empties.
    """
    if interpret is None:
        interpret = _interpret()
    n, d = x.shape
    k = centroids.shape[0]
    tile = _pick_tile(n)
    grid = n // tile
    dt = x.dtype
    cnorm = jnp.sum(centroids * centroids, axis=1)
    sums, counts = pl.pallas_call(
        _kmeans_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), dt),
            jax.ShapeDtypeStruct((1, k), dt),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * n * d * k, bytes_accessed=(n * d + n + 2 * k * d) * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, w[:, None], centroids, cnorm[None, :])
    return sums, counts[0]
