"""Batched sparse representation for TPU compute.

The reference's sparse story is a per-record ``SparseVector`` fed through
scalar BLAS (``BLAS.java`` dot on indices/values). On TPU, dynamic per-row
nnz breaks XLA's static-shape requirement, so batches use a padded ELL-style
layout: ``indices [n, max_nnz] int32`` and ``values [n, max_nnz]`` with
padding entries carrying index 0 / value 0 (value 0 makes padded lanes
no-ops in every product below — no masking needed).

This is the Criteo-scale path (BASELINE.json config #5): forward = gather +
row-sum; gradient = flat ``segment_sum`` scatter-add into the dense model,
both of which XLA lowers to efficient HBM gathers/scatters without a Pallas
kernel until profiling says otherwise.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.linalg import SparseVector, next_pow2


class BatchedCSR:
    """Padded batch of sparse rows with static shapes.

    Attributes:
        indices: int32 [n, max_nnz] column indices (0 where padded).
        values: float [n, max_nnz] entries (0.0 where padded).
        dim: dense width of each row.
    """

    def __init__(self, indices, values, dim: int):
        self.indices = jnp.asarray(indices, dtype=jnp.int32)
        self.values = jnp.asarray(values)
        if self.indices.shape != self.values.shape or self.indices.ndim != 2:
            raise ValueError(
                f"indices {self.indices.shape} and values {self.values.shape} "
                "must be equal 2-D shapes"
            )
        self.dim = int(dim)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_nnz(self) -> int:
        return self.indices.shape[1]

    # -- construction ------------------------------------------------------
    @staticmethod
    def pack_sparse_vectors(
        vectors: Iterable[SparseVector], max_nnz: int = None,
        dtype=np.float32, sort: bool = False,
    ):
        """Host-side ELL packing: returns numpy ``(indices, values, dim)``
        WITHOUT device placement — callers that shard (training) use this to
        avoid staging the full dataset in one device's HBM.

        ``sort=True`` additionally returns the pack-time global sort
        tables ``(indices, values, dim, perm, segment_ids)`` (see
        :func:`ell_sort_tables`) — the sorted-layout contract: sortedness
        is bought once at pack time, so every downstream gradient scatter
        runs with ``indices_are_sorted=True`` and no runtime sort."""
        vectors = list(vectors)
        if not vectors:
            raise ValueError("empty batch")
        dim = vectors[0].size()
        nnzs = [v.indices.size for v in vectors]
        width = max_nnz if max_nnz is not None else max(max(nnzs), 1)
        n = len(vectors)
        indices = np.zeros((n, width), dtype=np.int32)
        values = np.zeros((n, width), dtype=dtype)
        for i, v in enumerate(vectors):
            if v.size() != dim:
                raise ValueError(f"row {i} has dim {v.size()}, expected {dim}")
            k = min(v.indices.size, width)
            indices[i, :k] = v.indices[:k]
            values[i, :k] = v.values[:k]
        if sort:
            perm, segment_ids = ell_sort_tables(indices)
            return indices, values, dim, perm, segment_ids
        return indices, values, dim

    @staticmethod
    def from_sparse_vectors(
        vectors: Iterable[SparseVector], max_nnz: int = None, dtype=np.float32
    ) -> "BatchedCSR":
        indices, values, dim = BatchedCSR.pack_sparse_vectors(
            vectors, max_nnz, dtype
        )
        return BatchedCSR(indices, values, dim)

    @staticmethod
    def from_scipy(mat, dtype=np.float32) -> "BatchedCSR":
        """From a scipy.sparse matrix (CSR), padding rows to the max nnz."""
        mat = mat.tocsr()
        n, dim = mat.shape
        nnz_per_row = np.diff(mat.indptr)
        width = max(int(nnz_per_row.max()), 1) if n else 1
        indices = np.zeros((n, width), dtype=np.int32)
        values = np.zeros((n, width), dtype=dtype)
        for i in range(n):
            lo, hi = mat.indptr[i], mat.indptr[i + 1]
            k = hi - lo
            indices[i, :k] = mat.indices[lo:hi]
            values[i, :k] = mat.data[lo:hi]
        return BatchedCSR(indices, values, dim)

    # -- compute -----------------------------------------------------------
    def to_dense(self) -> jax.Array:
        """Densify to [n, dim] (for tests / small batches only)."""
        n = self.num_rows
        out = jnp.zeros((n, self.dim), dtype=self.values.dtype)
        rows = jnp.repeat(jnp.arange(n), self.max_nnz)
        return out.at[rows, self.indices.reshape(-1)].add(self.values.reshape(-1))

    def matvec(self, w, backend=None) -> jax.Array:
        """Row-wise sparse dot against a dense vector: [n].

        Routes through the kernel-backend gate
        (:mod:`flinkml_tpu.kernels`, site ``spmv``): the XLA
        gather-multiply-reduce by default, the row-tiled Pallas kernel —
        which bounds the gathered block to VMEM instead of materializing
        the whole ``[n, max_nnz]`` gather — when the gate or an explicit
        ``backend=`` selects it.
        """
        from flinkml_tpu import kernels

        w = jnp.asarray(w)
        return kernels.spmv(self.indices, self.values, w, backend=backend)

    def rmatvec(self, coeffs, backend=None) -> jax.Array:
        """Transpose product: X^T @ coeffs -> dense [dim].

        The sparse-gradient scatter-add (SURVEY.md §7 hard part (a)):
        flattens to one ``segment_sum`` so XLA emits a single HBM
        scatter. The lowering routes through the kernel-backend gate
        (:mod:`flinkml_tpu.kernels`, site ``segment_sum``): XLA by
        default, the Pallas streaming accumulator when the gate — or an
        explicit ``backend=`` — selects it.
        """
        from flinkml_tpu import kernels

        coeffs = jnp.asarray(coeffs)
        contrib = (self.values * coeffs[:, None]).reshape(-1)
        flat_idx = self.indices.reshape(-1)
        return kernels.segment_sum(contrib, flat_idx, self.dim,
                                   backend=backend)

    def slice_rows(self, start: int, stop: int) -> "BatchedCSR":
        return BatchedCSR(
            self.indices[start:stop], self.values[start:stop], self.dim
        )

    def sorted(self, nnz=None, place=None):
        """This batch as a :class:`~flinkml_tpu.table.SortedSparseColumn`
        — the pipeline-guaranteed sorted layout (pack-time global sort
        tables, ``indices_are_sorted`` recorded on the column).

        ``nnz`` optionally gives the true per-row nnz for the CSR
        ``indptr``; without it every cell counts (padding cells are the
        ELL index-0/value-0 no-op convention either way, so compute is
        unaffected — only host reconstruction of explicit zeros
        differs). ``place`` is the device placement (default
        ``jax.device_put``)."""
        from flinkml_tpu.table import SortedSparseColumn

        if place is None:
            place = jax.device_put
        idx = np.asarray(self.indices)
        n, width = idx.shape
        if nnz is None:
            nnz = np.full(n, width, dtype=np.int64)
        indptr = np.zeros(n + 1, dtype=np.int32)
        indptr[1:] = np.cumsum(np.asarray(nnz, dtype=np.int64))
        perm, segment_ids = ell_sort_tables(idx)
        return SortedSparseColumn(
            place(self.values), place(self.indices), place(indptr),
            place(perm), place(segment_ids), self.dim, n,
        )


def ell_sort_tables(indices: np.ndarray):
    """Pack-time global sort tables for a padded-ELL index block:
    ``(perm, segment_ids)``, both flat ``[rows * width] int32``.

    ``perm`` is a STABLE argsort of the flattened index block;
    ``segment_ids = flat[perm]`` is ascending by construction. A
    consumer's gradient scatter becomes
    ``segment_sum(take(contrib, perm), segment_ids,
    indices_are_sorted=True)`` — the sort is paid once here (on the
    prefetch worker thread, overlapped with compute), never at step
    time. Padding cells (index 0 / value 0) sort to the front as
    segment-0 no-op adds, so the tables cover the full padded block and
    are independent of the batch's logical row count."""
    flat = np.asarray(indices, dtype=np.int32).reshape(-1)
    perm = np.argsort(flat, kind="stable").astype(np.int32)
    return perm, flat[perm]


def pack_sorted_sparse_column(vectors: Sequence[SparseVector],
                              bucket: int = None, place=None,
                              dtype=np.float32):
    """Pack SparseVector rows into a
    :class:`~flinkml_tpu.table.SortedSparseColumn` (the prefetcher's
    sparse emission path — see that class for the layout contract).

    Rows are zero-padded to ``bucket`` (default: the fused executor's
    power-of-two row bucket) and the ELL width is quantized to the next
    power of two, so batch-size and nnz jitter inside a bucket reuse
    one compiled program downstream (zero retraces). ``place`` is the
    device placement (default ``jax.device_put``)."""
    from flinkml_tpu.pipeline_fusion import row_bucket
    from flinkml_tpu.table import SortedSparseColumn

    vectors = list(vectors)
    if not vectors:
        raise ValueError("empty batch")
    if place is None:
        place = jax.device_put
    n = len(vectors)
    if bucket is None:
        bucket = row_bucket(n)
    if bucket < n:
        raise ValueError(f"bucket {bucket} < {n} rows")
    dim = vectors[0].size()
    nnzs = np.fromiter((v.indices.size for v in vectors), dtype=np.int64,
                       count=n)
    width = next_pow2(max(int(nnzs.max()), 1))
    indices = np.zeros((bucket, width), dtype=np.int32)
    values = np.zeros((bucket, width), dtype=dtype)
    indptr = np.zeros(bucket + 1, dtype=np.int32)
    for i, v in enumerate(vectors):
        if v.size() != dim:
            raise ValueError(f"row {i} has dim {v.size()}, expected {dim}")
        k = v.indices.size
        indices[i, :k] = v.indices
        values[i, :k] = v.values
    indptr[1:n + 1] = np.cumsum(nnzs)
    indptr[n + 1:] = indptr[n]
    perm, segment_ids = ell_sort_tables(indices)
    host = np.empty(n, dtype=object)
    for i, v in enumerate(vectors):
        host[i] = v
    return SortedSparseColumn(
        place(values), place(indices), place(indptr), place(perm),
        place(segment_ids), dim, n, host_rows=host,
    )


# Elements per scoring dispatch (~64 MB of f32 working set); module-level
# so tests can shrink it to force the multi-chunk path.
_SCORING_CHUNK_ELEMS = 16 << 20


def sparse_margins(vectors: Sequence[SparseVector], coef,
                   max_buckets: int = 4) -> np.ndarray:
    """Row-wise dots ``X @ coef`` for SparseVector rows, skew-proof.

    ``coef`` may be a vector ``[d]`` (returns ``[n]``) or a class matrix
    ``[k, d]`` (returns ``[n, k]`` — multinomial scoring). Inference-side
    counterpart of the bucketed trainer: packs rows into nnz buckets
    (padded cells ≈ total nnz, vs n·max_nnz for a uniform
    :class:`BatchedCSR`), computes each bucket's gather-dot on device,
    and reassembles results in the caller's row order. O(nnz) memory at
    any skew and any dim.
    """
    indptr, indices, values, dim = csr_from_sparse_vectors(
        vectors, dtype=np.float32
    )
    # Same guarantee the dense path gets from `x @ coef` shape checking:
    # a dim mismatch must raise, not silently gather-clamp out-of-range
    # indices onto the last coefficient.
    coef = np.asarray(coef)
    n_coef = coef.shape[-1]
    if dim != n_coef:
        raise ValueError(
            f"features have dim {dim} but the model coefficient has "
            f"dim {n_coef}"
        )
    buckets, row_ids = pack_ell_buckets(
        indptr, indices, values, dim, max_buckets=max_buckets,
        dtype=np.float32,
    )
    n = indptr.size - 1
    multinomial = coef.ndim == 2
    k = coef.shape[0] if multinomial else 1
    coef_dev = jnp.asarray(coef.T if multinomial else coef, jnp.float32)
    out = np.empty((n, k) if multinomial else n, dtype=np.float32)
    for bucket, rows in zip(buckets, row_ids):
        width = bucket["indices"].shape[1]
        # The per-dispatch working set ([chunk, slots] values + indices +
        # the gathered coefficients) is bounded so scoring a million-row
        # batch cannot blow host/HBM memory, on either branch.
        chunk = max(1, _SCORING_CHUNK_ELEMS // max(1, width * k))
        for lo in range(0, rows.size, chunk):
            sl = slice(lo, lo + chunk)
            vb = jnp.asarray(bucket["values"][sl])       # [c, s]
            ib = jnp.asarray(bucket["indices"][sl])      # [c, s]
            if multinomial:
                # Gather [c, s, k], contract the slot axis.
                out[rows[sl]] = np.asarray(
                    jnp.einsum("rs,rsk->rk", vb, coef_dev[ib])
                )
            else:
                from flinkml_tpu import kernels

                out[rows[sl]] = np.asarray(kernels.spmv(ib, vb, coef_dev))
    return out


# ---------------------------------------------------------------------------
# nnz-bucketed ELL packing (skew-proof Criteo-scale layout)
# ---------------------------------------------------------------------------

def csr_from_sparse_vectors(vectors: Sequence[SparseVector],
                            dtype=np.float32):
    """Host CSR arrays ``(indptr, indices, values, dim)`` from SparseVectors.

    ``dtype`` bounds host staging memory — at Criteo scale (~1e9 nnz)
    float32 staging halves the transient footprint vs float64.
    """
    vectors = list(vectors)
    if not vectors:
        raise ValueError("empty batch")
    dim = vectors[0].size()
    nnzs = np.fromiter((v.indices.size for v in vectors), dtype=np.int64,
                       count=len(vectors))
    indptr = np.zeros(len(vectors) + 1, dtype=np.int64)
    np.cumsum(nnzs, out=indptr[1:])
    indices = np.empty(int(indptr[-1]), dtype=np.int32)
    values = np.empty(int(indptr[-1]), dtype=dtype)
    for i, v in enumerate(vectors):
        if v.size() != dim:
            raise ValueError(f"row {i} has dim {v.size()}, expected {dim}")
        lo, hi = indptr[i], indptr[i + 1]
        indices[lo:hi] = v.indices
        values[lo:hi] = v.values
    return indptr, indices, values, dim


def choose_ell_widths(nnz: np.ndarray, max_buckets: int = 4,
                      max_distinct: int = 256):
    """Optimal bucket widths for nnz-sorted rows (minimum padded cells).

    Uniform ELL pads every row to the dataset max — pathological under a
    skewed nnz distribution (round-1 VERDICT "weak" #3). Splitting the
    nnz-sorted rows into ≤ ``max_buckets`` groups, each padded to its own
    max, is solved exactly by DP over the distinct widths: the cost of a
    bucket covering sorted ranks (i, j] is ``count · width_j``. Distinct
    widths beyond ``max_distinct`` are first quantized up (cost model only
    — packing still pads to the chosen widths, correctness unaffected).

    Returns a sorted list of bucket max-widths (the last equals max(nnz),
    after quantization); every row belongs to the first bucket whose
    width ≥ its nnz.
    """
    nnz = np.asarray(nnz, dtype=np.int64)
    if nnz.size == 0:
        return [1]
    widths, counts = np.unique(np.maximum(nnz, 1), return_counts=True)
    if widths.size > max_distinct:
        step = int(np.ceil(widths.max() / max_distinct))
        q = np.maximum((widths + step - 1) // step * step, 1)
        qw, inv = np.unique(q, return_inverse=True)
        qc = np.zeros(qw.size, dtype=np.int64)
        np.add.at(qc, inv, counts)
        widths, counts = qw, qc
    V = widths.size
    G = min(max_buckets, V)
    prefix = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(counts, out=prefix[1:])
    INF = np.iinfo(np.int64).max
    # dp[g][j]: min cells covering the first j distinct widths with g buckets.
    dp = np.full((G + 1, V + 1), INF, dtype=np.int64)
    choice = np.zeros((G + 1, V + 1), dtype=np.int64)
    dp[0][0] = 0
    for g in range(1, G + 1):
        for j in range(1, V + 1):
            best, arg = INF, 0
            for i in range(j):
                if dp[g - 1][i] == INF:
                    continue
                c = dp[g - 1][i] + (prefix[j] - prefix[i]) * int(widths[j - 1])
                if c < best:
                    best, arg = c, i
            dp[g][j] = best
            choice[g][j] = arg
    # Fewer buckets can never beat more here (splitting is free), so read
    # the G-bucket solution and drop empty splits.
    bounds = []
    j = V
    for g in range(G, 0, -1):
        bounds.append(int(widths[j - 1]))
        j = int(choice[g][j])
        if j == 0:
            break
    return sorted(set(bounds))


def fill_ell(bi, bv, row_starts, counts, indices, values) -> None:
    """Vectorized CSR→ELL fill: write each row's ``counts[r]`` cells
    (sourced at ``row_starts[r]``) into the padded blocks ``bi``/``bv``
    in place — the one definition of the scatter-gather shared by
    :func:`pack_ell_buckets` and the streamed uniform pack."""
    counts = np.asarray(counts, dtype=np.int64)
    row_rep = np.repeat(np.arange(counts.size), counts)
    slot = np.arange(int(counts.sum()), dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts
    )
    src = np.repeat(np.asarray(row_starts, dtype=np.int64), counts) + slot
    bi[row_rep, slot] = indices[src]
    bv[row_rep, slot] = values[src]


def pack_ell_buckets(indptr, indices, values, dim: int,
                     max_buckets: int = 4, dtype=np.float32):
    """Pack CSR rows into nnz-bucketed ELL blocks.

    Returns ``(buckets, row_ids)`` where each bucket is a dict with
    ``indices [n_b, w_b] int32`` / ``values [n_b, w_b] dtype`` (padding
    entries index 0 / value 0, exactly as :class:`BatchedCSR`), and
    ``row_ids`` is a list of int64 arrays mapping bucket rows back to the
    caller's row order (for gathering labels/weights). Total padded cells
    = the DP optimum of :func:`choose_ell_widths` — ≈ total nnz for any
    realistic skew, vs ``n · max_nnz`` for uniform ELL.
    """
    indptr = np.asarray(indptr, dtype=np.int64)
    n = indptr.size - 1
    nnz = np.diff(indptr)
    bucket_widths = choose_ell_widths(nnz, max_buckets=max_buckets)
    edges = np.asarray(bucket_widths, dtype=np.int64)
    which = np.searchsorted(edges, np.maximum(nnz, 1))
    buckets, row_ids = [], []
    for b, width in enumerate(bucket_widths):
        rows = np.nonzero(which == b)[0]
        if rows.size == 0:
            continue
        w = int(width)
        bi = np.zeros((rows.size, w), dtype=np.int32)
        bv = np.zeros((rows.size, w), dtype=dtype)
        fill_ell(bi, bv, indptr[rows], nnz[rows], indices, values)
        buckets.append({"indices": bi, "values": bv})
        row_ids.append(rows)
    return buckets, row_ids


# Chunk width of the two-level running sum in chunked_run_totals. Within-
# chunk prefix sums bound the f32 cancellation error of a boundary
# difference by the CHUNK's magnitude (~eps·sqrt(C)·sigma) instead of the
# whole array's (~eps·sqrt(cells)·sigma — a fixed bias on small runs at
# 1e7 cells when the inputs are deterministic across steps).
CUMSUM_CHUNK = 65_536


def chunked_run_totals(contrib, ends):
    """Totals of contiguous runs of ``contrib`` (1-D ``[cells]`` or 2-D
    ``[cells, k]``, reduced over axis 0 per column) ending at inclusive
    indices ``ends`` (ascending; a repeated end differences to exactly
    0) — the sort-free segmented reduction behind the ``cumsum`` sparse
    gradient layout and the GBT histogram fast path.

    A single global running sum would give every boundary difference
    absolute error ~eps·|global prefix|; the two-level decomposition
    bounds it by the chunk scale instead: a run inside one chunk
    differences the LOCAL prefix sum, a run spanning chunks takes
    head/tail locally and the full chunks between from a chunk-prefix
    difference that is exactly 0 unless the run contains >= 1 full chunk
    — in which case its own magnitude is chunk-sized and the global
    error is relatively negligible. Verified against float64 at the
    1e7-cell bench shape (``tests/test_sparse_scale.py``)."""
    flat = contrib.ndim == 1
    if flat:
        contrib = contrib[:, None]
    cells, k = contrib.shape
    acc = contrib.dtype
    # Effective chunk width: inputs smaller than one chunk must not pad up
    # to the full 65536 rows — at the ALS cumsum layout ([chunk, k*k+k+1]
    # payload) a 4k-row chunk at rank ~100 would otherwise materialize a
    # multi-GB transient for a few-MB input. The error-bound rationale for
    # chunking is unaffected: an input smaller than one chunk has a single
    # chunk either way.
    C = min(CUMSUM_CHUNK, next_pow2(cells + 1))
    # Front-pad one zero cell so every boundary index shifts to >= 1 and
    # the "previous end" of the first run is index 0 (a zero); tail-pad
    # to a whole number of chunks.
    n_chunks = -(-(cells + 1) // C)
    pad_tail = n_chunks * C - (cells + 1)
    padded = jnp.concatenate([
        jnp.zeros((1, k), acc), contrib, jnp.zeros((pad_tail, k), acc)
    ])
    lcs = jnp.cumsum(padded.reshape(n_chunks, C, k), axis=1)
    chunk_tot = lcs[:, -1, :]                      # [n_chunks, k]
    chunk_prefix = jnp.cumsum(chunk_tot, axis=0)
    flat_lcs = lcs.reshape(-1, k)

    e1 = ends + 1
    s1 = jnp.concatenate([jnp.zeros((1,), ends.dtype), e1[:-1]])
    ce, cs = e1 // C, s1 // C
    local_e = jnp.take(flat_lcs, e1, axis=0)
    local_s = jnp.take(flat_lcs, s1, axis=0)
    same = (ce == cs)[:, None]
    # Spanning: tail of the start chunk + full chunks between (exactly 0
    # when ce == cs + 1) + head of the end chunk.
    tail = jnp.take(chunk_tot, cs, axis=0) - local_s
    between = jnp.take(chunk_prefix, jnp.maximum(ce - 1, 0), axis=0) - \
        jnp.take(chunk_prefix, cs, axis=0)
    out = jnp.where(same, local_e - local_s, tail + between + local_e)
    return out[:, 0] if flat else out


def run_boundary_tables(sorted_keys: np.ndarray):
    """Run boundaries of each ROW of ``sorted_keys [R, L]`` (each row
    ascending): ``(ends, cols)``, both ``[R, max_runs] int32`` — the
    pack-time companion of :func:`chunked_run_totals`. Padding repeats
    the last real end (whose running-sum difference is exactly 0) and
    the last real key. ``max_runs`` is at least 1 (an empty input yields
    a single zero-length table row)."""
    sorted_keys = np.asarray(sorted_keys)
    R, L = sorted_keys.shape
    per = []
    for row in range(R):
        s = sorted_keys[row]
        is_end = np.empty(L, np.bool_)
        is_end[:-1] = s[:-1] != s[1:]
        if L:
            is_end[-1] = True
        per.append(np.nonzero(is_end)[0].astype(np.int32))
    max_runs = max((e.size for e in per), default=1) or 1
    ends = np.full((R, max_runs), max(L - 1, 0), np.int32)
    cols = np.zeros((R, max_runs), np.int32)
    for row, e in enumerate(per):
        ends[row, : e.size] = e
        cols[row, : e.size] = sorted_keys[row, e]
        if e.size:
            cols[row, e.size:] = sorted_keys[row, e[-1]]
    return ends, cols
