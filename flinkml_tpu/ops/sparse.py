"""Batched sparse representation for TPU compute.

The reference's sparse story is a per-record ``SparseVector`` fed through
scalar BLAS (``BLAS.java`` dot on indices/values). On TPU, dynamic per-row
nnz breaks XLA's static-shape requirement, so batches use a padded ELL-style
layout: ``indices [n, max_nnz] int32`` and ``values [n, max_nnz]`` with
padding entries carrying index 0 / value 0 (value 0 makes padded lanes
no-ops in every product below — no masking needed).

This is the Criteo-scale path (BASELINE.json config #5): forward = gather +
row-sum; gradient = flat ``segment_sum`` scatter-add into the dense model,
both of which XLA lowers to efficient HBM gathers/scatters without a Pallas
kernel until profiling says otherwise.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.linalg import SparseVector


class BatchedCSR:
    """Padded batch of sparse rows with static shapes.

    Attributes:
        indices: int32 [n, max_nnz] column indices (0 where padded).
        values: float [n, max_nnz] entries (0.0 where padded).
        dim: dense width of each row.
    """

    def __init__(self, indices, values, dim: int):
        self.indices = jnp.asarray(indices, dtype=jnp.int32)
        self.values = jnp.asarray(values)
        if self.indices.shape != self.values.shape or self.indices.ndim != 2:
            raise ValueError(
                f"indices {self.indices.shape} and values {self.values.shape} "
                "must be equal 2-D shapes"
            )
        self.dim = int(dim)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_nnz(self) -> int:
        return self.indices.shape[1]

    # -- construction ------------------------------------------------------
    @staticmethod
    def pack_sparse_vectors(
        vectors: Iterable[SparseVector], max_nnz: int = None, dtype=np.float32
    ):
        """Host-side ELL packing: returns numpy ``(indices, values, dim)``
        WITHOUT device placement — callers that shard (training) use this to
        avoid staging the full dataset in one device's HBM."""
        vectors = list(vectors)
        if not vectors:
            raise ValueError("empty batch")
        dim = vectors[0].size()
        nnzs = [v.indices.size for v in vectors]
        width = max_nnz if max_nnz is not None else max(max(nnzs), 1)
        n = len(vectors)
        indices = np.zeros((n, width), dtype=np.int32)
        values = np.zeros((n, width), dtype=dtype)
        for i, v in enumerate(vectors):
            if v.size() != dim:
                raise ValueError(f"row {i} has dim {v.size()}, expected {dim}")
            k = min(v.indices.size, width)
            indices[i, :k] = v.indices[:k]
            values[i, :k] = v.values[:k]
        return indices, values, dim

    @staticmethod
    def from_sparse_vectors(
        vectors: Iterable[SparseVector], max_nnz: int = None, dtype=np.float32
    ) -> "BatchedCSR":
        indices, values, dim = BatchedCSR.pack_sparse_vectors(
            vectors, max_nnz, dtype
        )
        return BatchedCSR(indices, values, dim)

    @staticmethod
    def from_scipy(mat, dtype=np.float32) -> "BatchedCSR":
        """From a scipy.sparse matrix (CSR), padding rows to the max nnz."""
        mat = mat.tocsr()
        n, dim = mat.shape
        nnz_per_row = np.diff(mat.indptr)
        width = max(int(nnz_per_row.max()), 1) if n else 1
        indices = np.zeros((n, width), dtype=np.int32)
        values = np.zeros((n, width), dtype=dtype)
        for i in range(n):
            lo, hi = mat.indptr[i], mat.indptr[i + 1]
            k = hi - lo
            indices[i, :k] = mat.indices[lo:hi]
            values[i, :k] = mat.data[lo:hi]
        return BatchedCSR(indices, values, dim)

    # -- compute -----------------------------------------------------------
    def to_dense(self) -> jax.Array:
        """Densify to [n, dim] (for tests / small batches only)."""
        n = self.num_rows
        out = jnp.zeros((n, self.dim), dtype=self.values.dtype)
        rows = jnp.repeat(jnp.arange(n), self.max_nnz)
        return out.at[rows, self.indices.reshape(-1)].add(self.values.reshape(-1))

    def matvec(self, w) -> jax.Array:
        """Row-wise sparse dot against a dense vector: [n]."""
        w = jnp.asarray(w)
        return jnp.sum(self.values * w[self.indices], axis=1)

    def rmatvec(self, coeffs) -> jax.Array:
        """Transpose product: X^T @ coeffs -> dense [dim].

        The sparse-gradient scatter-add (SURVEY.md §7 hard part (a)):
        flattens to one ``segment_sum`` so XLA emits a single HBM scatter.
        """
        coeffs = jnp.asarray(coeffs)
        contrib = (self.values * coeffs[:, None]).reshape(-1)
        flat_idx = self.indices.reshape(-1)
        return jax.ops.segment_sum(contrib, flat_idx, num_segments=self.dim)

    def slice_rows(self, start: int, stop: int) -> "BatchedCSR":
        return BatchedCSR(
            self.indices[start:stop], self.values[start:stop], self.dim
        )
