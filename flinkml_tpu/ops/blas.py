"""BLAS facade — the numeric kernel layer.

Parity: ``flink-ml-core/.../ml/linalg/BLAS.java:26-91`` exposes
``asum/axpy/dot/norm2/scal/gemv`` over ``double[]`` via pure-Java netlib;
that facade is the *entire* kernel layer of the reference. Here every op is
a jax.numpy expression: XLA fuses elementwise chains and maps matmuls onto
the MXU, and the same functions trace cleanly inside ``jit``/``grad``/
``vmap``/``shard_map``.

Batched variants (``gemm``, ``batch_dot``, ``squared_distances``) are the
TPU-first additions: the reference calls gemv per row (e.g.
``KnnModel.java:72-197``); on TPU the batch dimension belongs in the kernel.

Functions accept jax or numpy arrays and return jax arrays. Precision policy:
computations run in the input dtype; algorithms choose float32 (TPU-native)
and tests may use float64 on CPU (x64 enabled in conftest).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def asum(x) -> Array:
    """Sum of absolute values. Parity: BLAS.java asum."""
    return jnp.sum(jnp.abs(x))


def axpy(a, x, y) -> Array:
    """a*x + y (functional: returns the result instead of mutating y).

    Parity: BLAS.java axpy — the reference mutates ``y`` in place; under XLA
    arrays are immutable and the fused result is returned.
    """
    return a * x + y


def dot(x, y) -> Array:
    """Vector dot product. Parity: BLAS.java dot."""
    return jnp.dot(x, y)


def norm2(x) -> Array:
    """Euclidean norm. Parity: BLAS.java norm2."""
    return jnp.sqrt(jnp.sum(x * x))


def scal(a, x) -> Array:
    """a*x (functional). Parity: BLAS.java scal."""
    return a * x


def gemv(alpha, matrix, x, beta=0.0, y=None, trans: bool = False) -> Array:
    """alpha * op(A) @ x + beta * y. Parity: BLAS.java gemv."""
    a = matrix.T if trans else matrix
    out = alpha * (a @ x)
    if y is not None:
        out = out + beta * y
    return out


# -- batched TPU-first additions -------------------------------------------

def gemm(a, b) -> Array:
    """Plain matmul (MXU path); inputs [m,k] @ [k,n]."""
    return a @ b


def batch_dot(xs, y) -> Array:
    """Row-wise dot of a batch [n, d] against a vector [d] -> [n]."""
    return xs @ y


def squared_distances(xs, ys) -> Array:
    """Pairwise squared L2 distances: [n, d] x [m, d] -> [n, m].

    Uses the (‖x‖² - 2x·y + ‖y‖²) expansion so the dominant cost is one
    [n,d]@[d,m] matmul on the MXU instead of an O(n·m·d) elementwise
    broadcast that would blow HBM.
    """
    xs = jnp.asarray(xs)
    ys = jnp.asarray(ys)
    x2 = jnp.sum(xs * xs, axis=-1, keepdims=True)
    y2 = jnp.sum(ys * ys, axis=-1, keepdims=True).T
    d2 = x2 - 2.0 * (xs @ ys.T) + y2
    return jnp.maximum(d2, 0.0)
