"""Model selection: ParamGridBuilder, CrossValidator,
TrainValidationSplit.

The tuning family of the wider Flink/Spark ML API (the reference
snapshot has none). A grid point is applied by setting params directly
on the owning stage instance (our ``Param`` descriptors are class-level,
so each grid entry names the stage it configures — this also makes grids
over stages nested inside a ``Pipeline`` work naturally), the estimator
is refit per fold, and the evaluator (any AlgoOperator producing a
single-row metric table, e.g. ``BinaryClassificationEvaluator``) scores
the held-out fold. The best configuration is refit on the full data.

TPU stance: each fold's fit IS the framework's device program; the
tuning loop is plain host orchestration around it, exactly like the
iteration runtime's stance that "the loop is the program".
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator, Estimator, Model
from flinkml_tpu.common_params import HasSeed
from flinkml_tpu.io import read_write
from flinkml_tpu.params import (
    BoolParam,
    FloatParam,
    IntParam,
    Param,
    ParamValidators,
    StringParam,
    WithParams,
)
from flinkml_tpu.table import Table

# One grid point: [(stage, param, value), ...]
ParamMap = List[Tuple[WithParams, Param, Any]]


class ParamGridBuilder:
    """Cartesian product of per-(stage, param) value lists.

    ::

        grid = (
            ParamGridBuilder()
            .add_grid(lr, LogisticRegression.REG, [0.0, 0.1])
            .add_grid(lr, LogisticRegression.MAX_ITER, [20, 50])
            .build()
        )   # 4 param maps
    """

    def __init__(self):
        self._grid: List[Tuple[WithParams, Param, Sequence[Any]]] = []

    def add_grid(
        self, stage: WithParams, param: Param, values: Sequence[Any]
    ) -> "ParamGridBuilder":
        if not values:
            raise ValueError(f"empty value list for param {param.name}")
        if stage.get_param(param.name) is None:
            raise ValueError(
                f"Parameter {param.name} is not defined on "
                f"{type(stage).__name__}"
            )
        self._grid.append((stage, param, list(values)))
        return self

    def build(self) -> List[ParamMap]:
        maps: List[ParamMap] = [[]]
        for stage, param, values in self._grid:
            maps = [
                m + [(stage, param, v)] for m in maps for v in values
            ]
        return maps


def _apply(param_map: ParamMap) -> None:
    for stage, param, value in param_map:
        stage.set(param, value)


def _metric_from(evaluator: AlgoOperator, table: Table,
                 metric_name: Optional[str]) -> float:
    (metrics,) = evaluator.transform(table)
    name = metric_name or metrics.column_names[0]
    return float(np.asarray(metrics.column(name))[0])


def _describe(param_map: ParamMap) -> Dict[str, Any]:
    return {
        f"{type(stage).__name__}.{param.name}": value
        for stage, param, value in param_map
    }


class _TuningParams(HasSeed):
    METRIC_NAME = StringParam(
        "metricName",
        "Which column of the evaluator's output to optimize "
        "(default: its first column).",
        None,
    )
    LARGER_BETTER = BoolParam(
        "largerBetter", "Whether larger metric values win.", True
    )


class _BestModelWrapper(Model):
    """Shared scaffold for the fitted tuning models: delegate transform to
    the winning inner model; persist it in a subdirectory."""

    def __init__(self):
        super().__init__()
        self.best_model: Optional[Model] = None
        self.best_index: int = -1
        self.avg_metrics: List[float] = []
        self.param_maps_description: List[Dict[str, Any]] = []

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        if self.best_model is None:
            raise ValueError("No best model; fit first or load")
        return self.best_model.transform(*inputs)

    def save(self, path: str) -> None:
        if self.best_model is None:
            raise ValueError("No best model; fit first or load")
        read_write.save_metadata(self, path, extra={
            "bestIndex": self.best_index,
            "avgMetrics": list(map(float, self.avg_metrics)),
            "paramMaps": self.param_maps_description,
        })
        self.best_model.save(read_write.stage_path(path, 0))

    @classmethod
    def load(cls, path: str):
        meta = read_write.load_metadata(
            path, expected_class_name=f"{cls.__module__}.{cls.__qualname__}"
        )
        model = cls()
        model.load_param_map_json(meta["paramMap"])
        model.best_index = int(meta["bestIndex"])
        model.avg_metrics = list(meta["avgMetrics"])
        model.param_maps_description = list(meta["paramMaps"])
        model.best_model = read_write.load_stage(read_write.stage_path(path, 0))
        return model


class CrossValidator(_TuningParams, Estimator):
    """k-fold cross-validated grid search.

    Construct with ``estimator``, ``estimator_param_maps`` (from
    :class:`ParamGridBuilder`), and ``evaluator``; ``numFolds`` seeded
    row splits. ``fit`` returns a :class:`CrossValidatorModel` whose
    ``avg_metrics`` align with the param maps and whose ``best_model``
    is refit on the full input.
    """

    NUM_FOLDS = IntParam(
        "numFolds", "Number of cross-validation folds.", 3,
        ParamValidators.gt(1),
    )

    def __init__(self, estimator: Estimator = None,
                 estimator_param_maps: List[ParamMap] = None,
                 evaluator: AlgoOperator = None):
        super().__init__()
        self.estimator = estimator
        self.estimator_param_maps = estimator_param_maps
        self.evaluator = evaluator

    def _check(self):
        if self.estimator is None or self.evaluator is None:
            raise ValueError("estimator and evaluator must be provided")
        if not self.estimator_param_maps:
            raise ValueError("estimator_param_maps must be a non-empty list")

    def fit(self, *inputs: Table) -> "CrossValidatorModel":
        (table,) = inputs
        self._check()
        k = self.get(self.NUM_FOLDS)
        n = table.num_rows
        if n < k:
            raise ValueError(f"{n} rows < numFolds={k}")
        rng = np.random.default_rng(self.get_seed())
        perm = rng.permutation(n)
        folds = np.array_split(perm, k)
        larger = self.get(self.LARGER_BETTER)
        metric_name = self.get(self.METRIC_NAME)
        avg_metrics = []
        for param_map in self.estimator_param_maps:
            scores = []
            for f in range(k):
                test_idx = folds[f]
                train_idx = np.concatenate(
                    [folds[g] for g in range(k) if g != f]
                )
                _apply(param_map)
                model = self.estimator.fit(table.take(train_idx))
                (scored,) = model.transform(table.take(test_idx))
                scores.append(
                    _metric_from(self.evaluator, scored, metric_name)
                )
            avg_metrics.append(float(np.mean(scores)))
        best = int(np.argmax(avg_metrics) if larger else np.argmin(avg_metrics))
        _apply(self.estimator_param_maps[best])
        best_model = self.estimator.fit(table)
        out = CrossValidatorModel()
        out.copy_params_from(self)
        out.best_model = best_model
        out.best_index = best
        out.avg_metrics = avg_metrics
        out.param_maps_description = [
            _describe(m) for m in self.estimator_param_maps
        ]
        return out


class CrossValidatorModel(_TuningParams, _BestModelWrapper):
    NUM_FOLDS = CrossValidator.NUM_FOLDS


class TrainValidationSplit(_TuningParams, Estimator):
    """Single train/validation split grid search (cheaper than k-fold)."""

    TRAIN_RATIO = FloatParam(
        "trainRatio", "Fraction of rows used for training.", 0.75,
        ParamValidators.in_range(0.0, 1.0, lower_inclusive=False,
                                 upper_inclusive=False),
    )

    def __init__(self, estimator: Estimator = None,
                 estimator_param_maps: List[ParamMap] = None,
                 evaluator: AlgoOperator = None):
        super().__init__()
        self.estimator = estimator
        self.estimator_param_maps = estimator_param_maps
        self.evaluator = evaluator

    _check = CrossValidator._check

    def fit(self, *inputs: Table) -> "TrainValidationSplitModel":
        (table,) = inputs
        self._check()
        n = table.num_rows
        n_train = int(n * self.get(self.TRAIN_RATIO))
        if not 0 < n_train < n:
            raise ValueError(
                f"trainRatio {self.get(self.TRAIN_RATIO)} leaves an empty "
                f"split for {n} rows"
            )
        rng = np.random.default_rng(self.get_seed())
        perm = rng.permutation(n)
        train_idx, val_idx = perm[:n_train], perm[n_train:]
        larger = self.get(self.LARGER_BETTER)
        metric_name = self.get(self.METRIC_NAME)
        metrics = []
        for param_map in self.estimator_param_maps:
            _apply(param_map)
            model = self.estimator.fit(table.take(train_idx))
            (scored,) = model.transform(table.take(val_idx))
            metrics.append(_metric_from(self.evaluator, scored, metric_name))
        best = int(np.argmax(metrics) if larger else np.argmin(metrics))
        _apply(self.estimator_param_maps[best])
        best_model = self.estimator.fit(table)
        out = TrainValidationSplitModel()
        out.copy_params_from(self)
        out.best_model = best_model
        out.best_index = best
        out.avg_metrics = metrics
        out.param_maps_description = [
            _describe(m) for m in self.estimator_param_maps
        ]
        return out


class TrainValidationSplitModel(_TuningParams, _BestModelWrapper):
    TRAIN_RATIO = TrainValidationSplit.TRAIN_RATIO
