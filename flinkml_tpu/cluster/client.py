"""Client half of the worker transport: one multiplexed connection.

A :class:`WorkerClient` owns one TCP connection to a worker and
multiplexes any number of in-flight requests over it, correlated by the
frame's request id. A single reader thread completes requests as
response/error frames arrive and sweeps per-request transport deadlines
between reads, so a silent worker surfaces as
:class:`~flinkml_tpu.cluster.errors.TransportTimeoutError` on exactly
the overdue requests — never as an unbounded block. When the connection
dies (EOF, reset, torn frame) every request still in flight fails with
:class:`~flinkml_tpu.cluster.errors.WorkerDiedError`: the typed signal
the serving router turns into retire-and-failover.

``submit`` is callback-style (the RemoteEngine completes a
``ServingRequest`` from the reader thread — no extra hop); ``call`` is
the synchronous convenience built on it.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Any, Callable, Dict, Optional

from flinkml_tpu.cluster import protocol
from flinkml_tpu.cluster.errors import (
    ConnectionClosedError,
    TransportError,
    TransportTimeoutError,
    WorkerDiedError,
)
from flinkml_tpu.cluster.errors import decode_error
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("cluster.client")

#: on_done callback: (payload_or_None, error_or_None) — exactly one set.
DoneCallback = Callable[[Optional[Dict[str, Any]],
                         Optional[BaseException]], None]


class _Inflight:
    __slots__ = ("deadline", "on_done")

    def __init__(self, deadline: Optional[float], on_done: DoneCallback):
        self.deadline = deadline
        self.on_done = on_done


class WorkerClient:
    """One connection to one worker; thread-safe."""

    def __init__(self, host: str, port: int, *,
                 max_payload: int = protocol.DEFAULT_MAX_PAYLOAD,
                 connect_timeout_s: float = 10.0,
                 on_transport_latency: Optional[
                     Callable[[float], None]] = None,
                 metrics_group: Optional[Any] = None):
        self.host = host
        self.port = port
        self.max_payload = int(max_payload)
        self.connect_timeout_s = float(connect_timeout_s)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._inflight: Dict[int, _Inflight] = {}
        self._ids = itertools.count(1)
        self._reader: Optional[threading.Thread] = None
        self._closed = False
        self._ever_connected = False
        self._on_transport_latency = on_transport_latency
        self._metrics = metrics_group
        self.reconnects_total = 0

    # -- lifecycle ---------------------------------------------------------
    @property
    def connected(self) -> bool:
        return self._sock is not None and not self._closed

    def connect(self) -> "WorkerClient":
        """Connect (or reconnect after a drop) and start the reader."""
        with self._state_lock:
            if self._sock is not None:
                return self
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
            self._closed = False
            if self._ever_connected:
                self.reconnects_total += 1
                if self._metrics is not None:
                    self._metrics.counter("reconnects_total")
            self._ever_connected = True
            self._reader = threading.Thread(
                target=self._read_loop, args=(sock,),
                name=f"cluster-client-{self.host}:{self.port}", daemon=True,
            )
            self._reader.start()
        return self

    def close(self) -> None:
        with self._state_lock:
            self._closed = True
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
        self._fail_all(WorkerDiedError("client closed"))

    # -- request path ------------------------------------------------------
    def submit(self, op: str, payload: Optional[Dict[str, Any]] = None,
               deadline: Optional[float] = None,
               on_done: Optional[DoneCallback] = None) -> int:
        """Send one request; ``on_done`` fires from the reader thread
        with the response payload or a typed error. ``deadline`` is
        absolute ``time.monotonic()`` — the client-side transport
        deadline, swept even if the worker never answers."""
        sock = self._sock
        if sock is None or self._closed:
            raise WorkerDiedError(
                f"no connection to worker {self.host}:{self.port}"
            )
        req_id = next(self._ids)
        body = {"op": op}
        if payload:
            body.update(payload)
        frame = protocol.encode_frame(
            protocol.REQUEST, req_id, body, self.max_payload
        )
        if on_done is not None:
            with self._state_lock:
                self._inflight[req_id] = _Inflight(deadline, on_done)
        try:
            with self._send_lock:
                sock.sendall(frame)
        except OSError as e:
            with self._state_lock:
                self._inflight.pop(req_id, None)
            self._drop(WorkerDiedError(f"send failed: {e}"))
            raise WorkerDiedError(f"send to worker failed: {e}") from e
        return req_id

    def call(self, op: str, payload: Optional[Dict[str, Any]] = None,
             timeout_s: Optional[float] = 30.0) -> Dict[str, Any]:
        """Synchronous RPC: raises the typed error the worker (or the
        transport) produced."""
        done = threading.Event()
        box: Dict[str, Any] = {}

        def _done(result, error):
            box["result"], box["error"] = result, error
            done.set()

        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        self.submit(op, payload, deadline=deadline, on_done=_done)
        # The reader thread sweeps the deadline; the extra grace only
        # covers a reader wedged in recv — it still surfaces typed.
        if not done.wait(None if timeout_s is None else timeout_s + 1.0):
            raise TransportTimeoutError(
                f"worker {self.host}:{self.port} did not answer "
                f"{op!r} within {timeout_s}s"
            )
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    # -- reader ------------------------------------------------------------
    def _read_loop(self, sock: socket.socket) -> None:
        # FrameReader accumulates partial frames across polls, so the
        # deadline-sweeping wakeups below never tear a frame mid-read.
        reader = protocol.FrameReader(sock, self.max_payload)
        while True:
            if self._closed or self._sock is not sock:
                return
            try:
                frame = reader.poll(timeout_s=0.05)
            except ConnectionClosedError:
                self._drop(WorkerDiedError(
                    f"worker {self.host}:{self.port} closed the "
                    "connection"
                ), sock)
                return
            except (TransportError, OSError) as e:
                self._drop(WorkerDiedError(
                    f"worker {self.host}:{self.port} transport broke: "
                    f"{type(e).__name__}: {e}"
                ), sock)
                return
            if frame is None:
                self._sweep_deadlines()
                continue
            ftype, req_id, payload = frame
            with self._state_lock:
                entry = self._inflight.pop(req_id, None)
            if entry is None:  # deadline-swept or never ours: discard
                continue
            if ftype == protocol.ERROR:
                self._complete(entry, None, decode_error(payload))
            else:
                self._complete(entry, payload, None)
            self._sweep_deadlines()

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        expired = []
        with self._state_lock:
            for req_id, entry in list(self._inflight.items()):
                if entry.deadline is not None and entry.deadline <= now:
                    expired.append((req_id, entry))
                    del self._inflight[req_id]
        for req_id, entry in expired:
            self._complete(entry, None, TransportTimeoutError(
                f"request {req_id} to worker {self.host}:{self.port} "
                "exceeded its transport deadline"
            ))

    def _complete(self, entry: _Inflight,
                  result: Optional[Dict[str, Any]],
                  error: Optional[BaseException]) -> None:
        try:
            entry.on_done(result, error)
        except Exception:  # noqa: BLE001 — a callback must not kill the reader
            _log.exception("on_done callback raised")

    def _drop(self, error: WorkerDiedError,
              sock: Optional[socket.socket] = None) -> None:
        """Connection is gone: detach it and fail everything in flight."""
        with self._state_lock:
            if sock is not None and self._sock is not sock:
                return  # a reconnect already replaced it
            dead, self._sock = self._sock, None
        if dead is not None:
            try:
                dead.close()
            except OSError:
                pass
        self._fail_all(error)

    def _fail_all(self, error: BaseException) -> None:
        with self._state_lock:
            pending = list(self._inflight.values())
            self._inflight.clear()
        for entry in pending:
            self._complete(entry, None, error)

    @property
    def inflight(self) -> int:
        with self._state_lock:
            return len(self._inflight)
