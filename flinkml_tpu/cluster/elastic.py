"""Elastic process worlds: world size = process count.

PR 7's elastic machinery (snapshot layout tags, ``rescale_world``,
``agree_resume_epoch``, the world-independent elastic feed) already
proves a world-4 run resumes bit-exactly at world 2 — but the "world"
there was simulated inside one process. This module makes the world
REAL: :class:`ElasticProcessWorld` launches one OS process per rank,
wires them to one rendezvous through the ``FLINKML_TPU_COORD_ADDR``
env family (the satellite contract of
:func:`~flinkml_tpu.parallel.distributed.init_distributed`), and — when
a rank dies (a :class:`~flinkml_tpu.faults.WorkerCrash`, a preemption,
an OOM kill) — relaunches the SURVIVORS as a compacted smaller world.
The resumed ranks find the dead world's snapshots via
``agree_resume_epoch`` and the checkpoint layout tags re-layout the
state to the new world size; this launcher only supplies real process
boundaries and the restart loop an orchestrator would.

Rank exit codes are the contract: 0 means the rank finished its work;
anything else means the rank was lost this round and the world shrinks
by the number of lost ranks (never below ``min_world``).
"""

from __future__ import annotations

import os
import socket
import subprocess
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from flinkml_tpu.cluster.errors import ClusterError
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("cluster.elastic")

#: The env-var rendezvous family init_distributed reads (satellite
#: contract: operator-launched processes and spawned workers share one
#: path).
COORD_ADDR_VAR = "FLINKML_TPU_COORD_ADDR"
WORLD_SIZE_VAR = "FLINKML_TPU_WORLD_SIZE"
RANK_VAR = "FLINKML_TPU_RANK"


def free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def rendezvous_env(rank: int, world: int, port: int,
                   base: Optional[Mapping[str, str]] = None
                   ) -> Dict[str, str]:
    """The child env for one rank of a ``world``-process rendezvous."""
    env = dict(base if base is not None else os.environ)
    env[COORD_ADDR_VAR] = f"127.0.0.1:{port}"
    env[WORLD_SIZE_VAR] = str(int(world))
    env[RANK_VAR] = str(int(rank))
    return env


class ElasticProcessWorld:
    """Launch/supervise one elastic multi-process run (see module
    docstring).

    ``argv_for_rank(rank, world, round_index)`` builds each rank's
    command line — the script it names must call ``init_distributed()``
    (env-driven) and resume from its checkpoint directory when one
    exists.
    """

    def __init__(
        self,
        argv_for_rank: Callable[[int, int, int], Sequence[str]],
        *,
        env: Optional[Mapping[str, str]] = None,
        workdir: Optional[str] = None,
        round_timeout_s: float = 300.0,
    ):
        self._argv_for_rank = argv_for_rank
        self._base_env = dict(env) if env is not None else None
        self._workdir = workdir
        self._round_timeout_s = float(round_timeout_s)
        self.rounds: List[Dict[str, object]] = []

    def _launch_round(self, world: int, round_index: int
                      ) -> Tuple[List[subprocess.Popen], List[str]]:
        port = free_port()
        procs: List[subprocess.Popen] = []
        logs: List[str] = []
        for rank in range(world):
            env = rendezvous_env(rank, world, port, base=self._base_env)
            env.setdefault("JAX_PLATFORMS", "cpu")
            log_path = None
            stderr = subprocess.DEVNULL
            if self._workdir is not None:
                log_path = os.path.join(
                    self._workdir,
                    f"round{round_index}-rank{rank}.log",
                )
                stderr = open(log_path, "wb")
            logs.append(log_path or "<devnull>")
            try:
                procs.append(subprocess.Popen(
                    [str(a) for a in
                     self._argv_for_rank(rank, world, round_index)],
                    env=env, stdout=stderr, stderr=stderr,
                    cwd=self._workdir,
                ))
            finally:
                if stderr is not subprocess.DEVNULL:
                    stderr.close()
        return procs, logs

    def run(self, world: int, *, min_world: int = 1,
            max_rounds: int = 4) -> int:
        """Run rounds until a world completes with every rank at exit 0.
        Each failed round shrinks the world by its lost ranks. Returns
        the world size that completed. Raises :class:`ClusterError`
        when the world would shrink below ``min_world`` or the round
        budget is spent."""
        world = int(world)
        for round_index in range(int(max_rounds)):
            t0 = time.monotonic()
            procs, logs = self._launch_round(world, round_index)
            rcs, crashed = self._wait_round(procs)
            lost = len(crashed)
            self.rounds.append({
                "round": round_index, "world": world, "exit_codes": rcs,
                "lost": lost, "elapsed_s": time.monotonic() - t0,
                "logs": logs,
            })
            if lost == 0:
                _log.info("elastic world %d completed in round %d",
                          world, round_index)
                return world
            survivors = world - lost
            _log.warning(
                "elastic round %d: lost %d of %d ranks (exit codes %s); "
                "resuming at world %d", round_index, lost, world, rcs,
                survivors,
            )
            if survivors < int(min_world):
                raise ClusterError(
                    f"world shrank below min_world={min_world} "
                    f"(survivors {survivors}); rounds: {self.rounds}"
                )
            world = survivors
        raise ClusterError(
            f"no round completed within {max_rounds} rounds; "
            f"rounds: {self.rounds}"
        )

    def _wait_round(self, procs: List[subprocess.Popen]
                    ) -> Tuple[List[int], List[int]]:
        """Wait for every rank → ``(exit_codes, crashed_ranks)``. Once
        ANY rank dies nonzero on its own, give the rest a short grace
        (a lost peer wedges collectives, so they rarely finish) then
        terminate them — ranks WE signalled are survivors of the next
        round, not losses; only self-inflicted deaths shrink the
        world."""
        deadline = time.monotonic() + self._round_timeout_s
        while time.monotonic() < deadline:
            states = [p.poll() for p in procs]
            if all(s is not None for s in states):
                crashed = [i for i, s in enumerate(states) if s != 0]
                return [int(s) for s in states], crashed
            if any(s is not None and s != 0 for s in states):
                grace = time.monotonic() + 10.0
                while time.monotonic() < grace:
                    if all(p.poll() is not None for p in procs):
                        break
                    time.sleep(0.1)
                # Everyone dead-by-now of its own accord is a loss;
                # everyone still running is merely interrupted.
                crashed = [
                    i for i, p in enumerate(procs)
                    if p.poll() is not None and p.poll() != 0
                ]
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(10.0)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait(5.0)
                return [int(p.poll()) for p in procs], crashed
            time.sleep(0.1)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(5.0)
        raise ClusterError(
            f"elastic round timed out after {self._round_timeout_s}s"
        )
