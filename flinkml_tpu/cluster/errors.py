"""Typed errors for the multi-process worker runtime.

The transport's whole contract is that a failure is never a hung socket
or a mystery ``EOFError`` — every way a frame exchange can go wrong has
a distinct type, because the serving router treats different failures
differently (schema errors propagate, overloads fail over and trip
DRAINING, everything else retires the replica — see
``flinkml_tpu/serving/router.py``):

- :class:`FrameError` — the byte stream itself is broken: wrong magic,
  or the peer closed mid-frame (a torn frame). The connection is
  unusable; in-flight requests on it fail with
  :class:`WorkerDiedError`.
- :class:`OversizedFrameError` — a frame header declares a payload over
  the negotiated cap. Raised on the SEND side before any byte leaves
  (the embedding-exchange guard: batch-sized payloads only, never a
  vocab-sized transfer) and on the RECEIVE side before the payload is
  read (a misbehaving peer cannot make us allocate its lie).
- :class:`TransportTimeoutError` — a deadline expired mid-exchange
  (including mid-read of a frame's own bytes). Also a
  :class:`TimeoutError`, mirroring
  :class:`~flinkml_tpu.serving.errors.ServingTimeoutError`.
- :class:`WorkerDiedError` — the worker process is gone (clean EOF,
  connection reset, or a nonzero exit): every request in flight on that
  connection fails with this, which the router maps to
  record-failure → retire, exactly like an in-process replica death.
- :class:`WorkerSpawnError` — the child never produced its ready line
  (bad spec, import failure, spawn deadline).
- :class:`RemoteError` — the worker reported an exception type this
  process does not recognize; carries the remote type name and message.

Errors that ARE recognized cross the boundary as themselves: a worker
raising :class:`~flinkml_tpu.serving.errors.ServingSchemaError` surfaces
client-side as ``ServingSchemaError``, so the router's typed-outcome
table needs no cluster-specific rows (see :func:`decode_error`).
"""

from __future__ import annotations

from typing import Any, Dict, Type


class ClusterError(RuntimeError):
    """Base of every cluster-runtime error."""


class TransportError(ClusterError):
    """Base of transport-layer (framing/connection) errors."""


class FrameError(TransportError):
    """The byte stream is not a valid frame sequence: bad magic bytes,
    or the peer closed the connection mid-frame (torn frame)."""


class ConnectionClosedError(FrameError):
    """Clean EOF at a frame boundary — the peer hung up between frames
    (distinct from a torn frame so a reader loop can exit quietly)."""


class OversizedFrameError(TransportError):
    """A frame payload exceeds the size cap — refused before any
    payload byte is sent or read."""


class TransportTimeoutError(TransportError, TimeoutError):
    """A transport deadline expired (including mid-read of a frame)."""


class WorkerDiedError(TransportError):
    """The worker process died (EOF/reset/exit) with requests in
    flight; each fails with this and the router retires the replica."""


class WorkerSpawnError(ClusterError):
    """A worker child process failed to come up (no ready line within
    the spawn deadline, or it exited during startup)."""


class RemoteError(ClusterError):
    """The worker raised an exception type unknown to this process;
    carries the remote type name and message."""

    def __init__(self, etype: str, message: str):
        super().__init__(f"{etype}: {message}")
        self.etype = etype
        self.remote_message = message


def _raisable_types() -> Dict[str, Type[BaseException]]:
    """Exception types allowed to cross the process boundary AS
    THEMSELVES. Anything else arrives as :class:`RemoteError` — error
    frames carry (type name, message), never pickled exception objects,
    so a worker cannot make the client construct arbitrary types."""
    from flinkml_tpu import faults
    from flinkml_tpu.serving import errors as serving_errors

    out: Dict[str, Type[BaseException]] = {
        cls.__name__: cls
        for cls in (
            ClusterError, TransportError, FrameError,
            ConnectionClosedError, OversizedFrameError,
            TransportTimeoutError, WorkerDiedError, WorkerSpawnError,
        )
    }
    for name in serving_errors.__all__ if hasattr(
            serving_errors, "__all__") else dir(serving_errors):
        obj = getattr(serving_errors, name, None)
        if isinstance(obj, type) and issubclass(obj, BaseException):
            out[name] = obj
    out["FaultInjected"] = faults.FaultInjected
    out["ValueError"] = ValueError
    out["KeyError"] = KeyError
    out["TimeoutError"] = TimeoutError
    return out


def encode_error(exc: BaseException) -> Dict[str, Any]:
    """The JSON/pickle-safe ERROR-frame payload for ``exc``."""
    return {"etype": type(exc).__name__, "message": str(exc)}


def decode_error(payload: Dict[str, Any]) -> BaseException:
    """Rebuild a typed exception from an ERROR-frame payload: a known
    type reconstructs as itself (message-only constructor), an unknown
    one becomes :class:`RemoteError` carrying the remote type name."""
    etype = str(payload.get("etype", "RemoteError"))
    message = str(payload.get("message", ""))
    cls = _raisable_types().get(etype)
    if cls is None:
        return RemoteError(etype, message)
    try:
        return cls(message)
    except Exception:  # constructor wants more args — degrade, loudly
        return RemoteError(etype, message)
