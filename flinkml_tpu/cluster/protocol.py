"""Length-prefixed frame protocol for worker IPC (localhost TCP).

Wire format (all integers big-endian)::

    +-------+------+------------+-----------+------------------+
    | magic | type | request id | length    | payload          |
    | 4 B   | 1 B  | 8 B        | 8 B       | `length` bytes   |
    +-------+------+------------+-----------+------------------+

- ``magic`` is ``b"FMC1"`` — protocol/version tag; anything else is a
  :class:`~flinkml_tpu.cluster.errors.FrameError` (the stream is not
  ours, or it de-synced).
- ``type`` is one of :data:`REQUEST` / :data:`RESPONSE` /
  :data:`ERROR`.
- ``request id`` correlates a response (or error) frame with its
  request — the client multiplexes any number of in-flight requests on
  one connection.
- ``length`` is capped (:data:`DEFAULT_MAX_PAYLOAD`, ~64 MiB): the
  sender refuses an oversized payload before writing a byte, and the
  receiver refuses on the HEADER, before allocating or reading the
  payload — a misbehaving peer cannot make either side buffer a
  vocab-sized transfer
  (:class:`~flinkml_tpu.cluster.errors.OversizedFrameError`).
- ``payload`` is a pickled dict (protocol 5 — numpy columns ride as
  contiguous buffers). Error frames carry ``{"etype", "message"}``
  only, never pickled exception objects (see
  :func:`flinkml_tpu.cluster.errors.decode_error`).

Deadlines are enforced PER BYTE, not per frame: :func:`recv_frame`
slices its socket timeout against an absolute monotonic deadline, so a
peer that sends half a frame and stalls surfaces as
:class:`~flinkml_tpu.cluster.errors.TransportTimeoutError` when the
deadline passes — mid-read, not after an unbounded block. EOF at a
frame boundary is the distinct
:class:`~flinkml_tpu.cluster.errors.ConnectionClosedError` (a clean
hang-up); EOF anywhere inside a frame is a torn frame
(:class:`~flinkml_tpu.cluster.errors.FrameError`).

This module is deliberately free of jax imports — the framing tests
exercise it against scripted sockets without paying a backend init.
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import time
from typing import Any, Dict, Optional, Tuple

from flinkml_tpu.cluster.errors import (
    ConnectionClosedError,
    FrameError,
    OversizedFrameError,
    TransportTimeoutError,
)

MAGIC = b"FMC1"
REQUEST = 0x01
RESPONSE = 0x02
ERROR = 0x03

_HEADER = struct.Struct(">4sBQQ")
HEADER_SIZE = _HEADER.size

#: Per-frame payload cap. Generous for batch-sized serving payloads
#: (a 1024-row float64 batch of a few hundred features is ~4 MB) while
#: refusing vocab-sized embedding-table transfers outright.
DEFAULT_MAX_PAYLOAD = 64 * 1024 * 1024

#: Socket-timeout slice used to poll the deadline while reading.
_POLL_S = 0.25


def dumps(payload: Dict[str, Any]) -> bytes:
    return pickle.dumps(payload, protocol=5)


def loads(raw: bytes) -> Dict[str, Any]:
    return pickle.loads(raw)


def encode_frame(ftype: int, request_id: int, payload: Dict[str, Any],
                 max_payload: int = DEFAULT_MAX_PAYLOAD) -> bytes:
    """Serialize one frame; refuses oversized payloads before building
    the buffer a send would write."""
    raw = dumps(payload)
    if len(raw) > max_payload:
        raise OversizedFrameError(
            f"frame payload is {len(raw)} bytes > cap {max_payload}; "
            "split the request (batch-sized payloads only)"
        )
    return _HEADER.pack(MAGIC, ftype, request_id, len(raw)) + raw


def send_frame(sock: socket.socket, ftype: int, request_id: int,
               payload: Dict[str, Any],
               max_payload: int = DEFAULT_MAX_PAYLOAD) -> None:
    sock.sendall(encode_frame(ftype, request_id, payload, max_payload))


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float]) -> bytes:
    """Read exactly ``n`` bytes, polling ``deadline`` (absolute
    ``time.monotonic()``) between socket-timeout slices. Raises
    :class:`ConnectionClosedError` on EOF at offset 0,
    :class:`FrameError` on EOF mid-buffer (torn), and
    :class:`TransportTimeoutError` when the deadline passes mid-read."""
    buf = io.BytesIO()
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeoutError(
                    f"transport deadline expired mid-read "
                    f"({got}/{n} bytes)"
                )
            sock.settimeout(min(_POLL_S, remaining))
        else:
            sock.settimeout(_POLL_S)
        try:
            chunk = sock.recv(n - got)
        except socket.timeout:
            continue
        if not chunk:
            if got == 0:
                raise ConnectionClosedError("peer closed the connection")
            raise FrameError(
                f"torn frame: peer closed after {got}/{n} bytes"
            )
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def recv_frame(
    sock: socket.socket,
    deadline: Optional[float] = None,
    max_payload: int = DEFAULT_MAX_PAYLOAD,
) -> Tuple[int, int, Dict[str, Any]]:
    """Read one frame → ``(type, request_id, payload)``. The deadline
    covers header AND payload bytes; the payload length is validated
    against ``max_payload`` before a payload byte is read."""
    header = _recv_exact(sock, HEADER_SIZE, deadline)
    magic, ftype, request_id, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(
            f"bad frame magic {magic!r} (expected {MAGIC!r}); "
            "stream is de-synced or not a cluster transport"
        )
    if length > max_payload:
        raise OversizedFrameError(
            f"peer declared a {length}-byte payload > cap {max_payload}; "
            "refusing to read it"
        )
    raw = _recv_exact(sock, length, deadline) if length else b""
    try:
        payload = loads(raw)
    except Exception as e:
        raise FrameError(f"undecodable frame payload: {e}") from e
    return ftype, request_id, payload


class FrameReader:
    """Incremental frame parser for a reader loop that must wake on a
    cadence (to sweep request deadlines) WITHOUT tearing a partially
    received frame: bytes accumulate across :meth:`poll` calls, so a
    frame larger than one ``recv`` — or one that straddles two polls —
    reassembles instead of de-syncing the stream.

    ``poll`` returns one complete frame or ``None`` at the timeout;
    it raises the same typed errors as :func:`recv_frame` (bad magic,
    oversized header, torn frame at EOF, clean close)."""

    def __init__(self, sock: socket.socket,
                 max_payload: int = DEFAULT_MAX_PAYLOAD):
        self._sock = sock
        self._max_payload = int(max_payload)
        self._buf = bytearray()

    def poll(self, timeout_s: float = _POLL_S
             ) -> Optional[Tuple[int, int, Dict[str, Any]]]:
        frame = self._try_parse()
        if frame is not None:
            return frame
        self._sock.settimeout(timeout_s)
        try:
            chunk = self._sock.recv(1 << 20)
        except socket.timeout:
            return None
        if not chunk:
            if self._buf:
                raise FrameError(
                    f"torn frame: peer closed with {len(self._buf)} "
                    "buffered bytes mid-frame"
                )
            raise ConnectionClosedError("peer closed the connection")
        self._buf.extend(chunk)
        return self._try_parse()

    def _try_parse(self) -> Optional[Tuple[int, int, Dict[str, Any]]]:
        if len(self._buf) < HEADER_SIZE:
            return None
        magic, ftype, request_id, length = _HEADER.unpack_from(self._buf)
        if magic != MAGIC:
            raise FrameError(
                f"bad frame magic {bytes(magic)!r} (expected {MAGIC!r})"
            )
        if length > self._max_payload:
            raise OversizedFrameError(
                f"peer declared a {length}-byte payload > cap "
                f"{self._max_payload}; refusing to read it"
            )
        if len(self._buf) < HEADER_SIZE + length:
            return None
        raw = bytes(self._buf[HEADER_SIZE:HEADER_SIZE + length])
        del self._buf[:HEADER_SIZE + length]
        try:
            payload = loads(raw)
        except Exception as e:
            raise FrameError(f"undecodable frame payload: {e}") from e
        return ftype, request_id, payload
