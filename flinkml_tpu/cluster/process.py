"""Spawning and supervising worker child processes.

:class:`WorkerSpec` is everything a child needs to stand up its engine,
pickled to a file the child's ``main`` reads (models ride as their own
pickle blob so a registry-backed worker can instead open the registry
directory itself). :class:`WorkerProcess` spawns
``python -m flinkml_tpu.cluster.worker``, pins the child's device slice
via env (``XLA_FLAGS --xla_force_host_platform_device_count`` on the
CPU mesh — each worker owns its OWN XLA executor pool and its own GIL,
which is the entire point of the subsystem), points it at the shared
compile-cache directory, and waits for the single JSON ready line on
the child's stdout. ``spawn_ms`` is recorded for the ``cluster.*``
metrics group; a child that exits or stays silent past the deadline is
a typed :class:`~flinkml_tpu.cluster.errors.WorkerSpawnError` with the
tail of the child's stderr attached (the stuck-worker runbook's first
artifact — see ``docs/development/cluster.md``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import select
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, Mapping, Optional, Sequence

from flinkml_tpu.cluster.errors import WorkerSpawnError
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("cluster.process")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


@dataclasses.dataclass
class WorkerSpec:
    """The child's construction record (see module docstring)."""

    example: Dict[str, Any]                 # column name -> host array
    source: Dict[str, Any]                  # {"kind": "model"|"registry", ...}
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    output_cols: Optional[Sequence[str]] = None
    name: str = "worker"
    compile_cache_dir: Optional[str] = None
    max_payload: Optional[int] = None

    @classmethod
    def for_model(cls, model: Any, example_columns: Dict[str, Any],
                  **kw) -> "WorkerSpec":
        return cls(
            example=dict(example_columns),
            source={"kind": "model", "blob": pickle.dumps(model, protocol=5)},
            **kw,
        )

    @classmethod
    def for_registry(cls, root: str, example_columns: Dict[str, Any],
                     **kw) -> "WorkerSpec":
        return cls(
            example=dict(example_columns),
            source={"kind": "registry", "root": os.path.abspath(root)},
            **kw,
        )

    def write(self, path: str) -> str:
        with open(path, "wb") as f:
            pickle.dump(dataclasses.asdict(self), f, protocol=5)
        return path


class WorkerProcess:
    """One supervised worker child."""

    def __init__(self, spec: WorkerSpec, *,
                 name: Optional[str] = None,
                 devices_per_worker: Optional[int] = 1,
                 env: Optional[Mapping[str, str]] = None,
                 spawn_timeout_s: float = 180.0,
                 python: str = sys.executable,
                 workdir: Optional[str] = None):
        self.spec = spec
        self.name = name or spec.name
        self.devices_per_worker = devices_per_worker
        self._extra_env = dict(env or {})
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.python = python
        safe = self.name.replace("/", "-").replace(os.sep, "-")
        self._workdir = workdir or tempfile.mkdtemp(
            prefix=f"flinkml-worker-{safe}-"
        )
        self._proc: Optional[subprocess.Popen] = None
        self.host = "127.0.0.1"
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        self.spawn_ms: Optional[float] = None
        self.stderr_path = os.path.join(self._workdir, "stderr.log")

    @property
    def workdir(self) -> str:
        """The child's scratch directory (spec file, stderr log)."""
        return self._workdir

    # -- lifecycle ---------------------------------------------------------
    def spawn(self) -> "WorkerProcess":
        """Start the child and block until its ready line (or raise
        :class:`WorkerSpawnError` with the stderr tail)."""
        t0 = time.monotonic()
        spec_path = self.spec.write(
            os.path.join(self._workdir, "spec.pkl")
        )
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.devices_per_worker is not None:
            # The child's device slice: its OWN virtual-device count,
            # not the parent's (a worker is its own XLA world).
            env["XLA_FLAGS"] = _replace_device_count_flag(
                env.get("XLA_FLAGS", ""), int(self.devices_per_worker)
            )
        env["PYTHONPATH"] = os.pathsep.join(
            x for x in (_REPO_ROOT, env.get("PYTHONPATH")) if x
        )
        env.update(self._extra_env)
        stderr = open(self.stderr_path, "ab")
        try:
            self._proc = subprocess.Popen(
                [self.python, "-m", "flinkml_tpu.cluster.worker",
                 spec_path],
                stdout=subprocess.PIPE, stderr=stderr, env=env,
            )
        finally:
            stderr.close()
        ready = self._await_ready(t0)
        self.port = int(ready["port"])
        self.pid = int(ready["pid"])
        self.spawn_ms = (time.monotonic() - t0) * 1000.0
        _log.info("worker %s up: pid %d port %d in %.0f ms "
                  "(engine stage %.0f ms)", self.name, self.pid,
                  self.port, self.spawn_ms,
                  ready.get("spawn_stage_ms", -1.0))
        return self

    def _await_ready(self, t0: float) -> Dict[str, Any]:
        assert self._proc is not None and self._proc.stdout is not None
        deadline = t0 + self.spawn_timeout_s
        out = self._proc.stdout
        line = b""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.kill()
                raise WorkerSpawnError(
                    f"worker {self.name} produced no ready line within "
                    f"{self.spawn_timeout_s}s; stderr tail:\n"
                    f"{self._stderr_tail()}"
                )
            if self._proc.poll() is not None:
                raise WorkerSpawnError(
                    f"worker {self.name} exited rc={self._proc.returncode} "
                    f"during startup; stderr tail:\n{self._stderr_tail()}"
                )
            rl, _, _ = select.select([out], [], [], min(0.25, remaining))
            if not rl:
                continue
            line = out.readline()
            if not line:
                continue
            try:
                ready = json.loads(line)
            except ValueError:
                continue  # stray stdout noise; keep waiting for ours
            if ready.get("ready"):
                return ready

    def _stderr_tail(self, n: int = 2000) -> str:
        try:
            with open(self.stderr_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return "<no stderr captured>"

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    @property
    def returncode(self) -> Optional[int]:
        return None if self._proc is None else self._proc.poll()

    def terminate(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()

    def kill(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()

    def join(self, timeout_s: Optional[float] = 10.0) -> Optional[int]:
        if self._proc is None:
            return None
        try:
            return self._proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            return None


def _replace_device_count_flag(flags: str, count: int) -> str:
    """Set ``--xla_force_host_platform_device_count=count`` in an
    ``XLA_FLAGS`` string, replacing any inherited value (the parent's
    virtual-device count is about the PARENT's mesh)."""
    kept = [
        t for t in flags.split()
        if not t.startswith("--xla_force_host_platform_device_count")
    ]
    kept.append(f"--xla_force_host_platform_device_count={int(count)}")
    return " ".join(kept)
