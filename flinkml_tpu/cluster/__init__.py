"""Multi-process worker runtime: "N replicas" means N processes.

The reference system's runtime is multi-process task managers
exchanging data over Netty; the reproduction's serving/runtime layers
were single-process SPMD until this subsystem. The pieces:

- :mod:`~flinkml_tpu.cluster.protocol` / :mod:`~flinkml_tpu.cluster
  .client` — the length-prefixed local transport (request ids,
  per-byte deadlines, typed error frames);
- :mod:`~flinkml_tpu.cluster.worker` — the child harness (one
  ServingEngine behind the transport, warm via the shared compile
  cache, ``cluster.worker`` fault seam);
- :mod:`~flinkml_tpu.cluster.process` — spawn/supervise children;
- :mod:`~flinkml_tpu.cluster.remote` — the engine adapter the serving
  router dispatches over, unchanged;
- :mod:`~flinkml_tpu.cluster.pool` — :class:`ClusterPool`, a
  ReplicaPool of worker processes, plus cross-process lease reclaim
  and batch-sized embedding row exchange;
- :mod:`~flinkml_tpu.cluster.elastic` — elastic process worlds (world
  size = process count; crash → resume at the smaller world).

See ``docs/development/cluster.md``.
"""

from flinkml_tpu.cluster.client import WorkerClient
from flinkml_tpu.cluster.elastic import (
    COORD_ADDR_VAR,
    RANK_VAR,
    WORLD_SIZE_VAR,
    ElasticProcessWorld,
    free_port,
    rendezvous_env,
)
from flinkml_tpu.cluster.errors import (
    ClusterError,
    ConnectionClosedError,
    FrameError,
    OversizedFrameError,
    RemoteError,
    TransportError,
    TransportTimeoutError,
    WorkerDiedError,
    WorkerSpawnError,
)
from flinkml_tpu.cluster.pool import (
    ClusterPool,
    fetch_embedding_rows,
    reclaim_worker_leases,
)
from flinkml_tpu.cluster.process import WorkerProcess, WorkerSpec
from flinkml_tpu.cluster.remote import RemoteEngine

__all__ = [
    "COORD_ADDR_VAR",
    "RANK_VAR",
    "WORLD_SIZE_VAR",
    "ClusterError",
    "ClusterPool",
    "ConnectionClosedError",
    "ElasticProcessWorld",
    "FrameError",
    "OversizedFrameError",
    "RemoteEngine",
    "RemoteError",
    "TransportError",
    "TransportTimeoutError",
    "WorkerClient",
    "WorkerDiedError",
    "WorkerProcess",
    "WorkerSpawnError",
    "WorkerSpec",
    "fetch_embedding_rows",
    "free_port",
    "reclaim_worker_leases",
    "rendezvous_env",
]
