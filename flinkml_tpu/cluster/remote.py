"""RemoteEngine: a worker process behind the ServingEngine surface.

The serving :class:`~flinkml_tpu.serving.router.Router` and
:class:`~flinkml_tpu.serving.pool.ReplicaPool` touch an engine through
a narrow contract — ``submit`` returning a pending whose ``.request``
makes CAS terminal transitions (complete/fail/abandon, waking the
router's race event), ``config.max_queue_rows``,
``_batcher.queued_rows`` as the balance signal, start/stop/running/
swap_to/``_metrics``. :class:`RemoteEngine` implements exactly that
contract over the worker transport, so every pool behavior — least-
outstanding-rows balance, typed failover, gray-fail abandonment and
hedging, health quarantine, hot swap — works unchanged whether the
replica is a thread or a process.

The pieces are deliberately REUSED, not imitated: requests are real
:class:`~flinkml_tpu.serving.batcher.ServingRequest` objects (same CAS
semantics, same race-event wiring) and handles are real
:class:`~flinkml_tpu.serving.engine.PendingPrediction` objects; the
transport client completes them from its reader thread. Schema
validation runs CLIENT-side (`ServingEngine._normalize`, borrowed) so a
malformed request costs no round trip and raises the identical typed
error. Admission is also client-side: ``max_queue_rows`` bounds the
rows in flight to one worker, and exceeding it raises the same
:class:`~flinkml_tpu.serving.errors.ServingOverloadError` the in-process
engine raises — which is what trips the router's failover → DRAINING
ladder.

Failure mapping: a worker's typed serving error re-raises as itself
(the error-frame registry); a dead worker fails every in-flight request
with :class:`~flinkml_tpu.cluster.errors.WorkerDiedError`, which the
router's catch-all turns into record-failure → retire — the same path
an in-process replica death takes.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Mapping, Optional, Sequence, Union

import numpy as np

from flinkml_tpu.cluster import protocol
from flinkml_tpu.cluster.client import WorkerClient
from flinkml_tpu.cluster.errors import TransportError, WorkerDiedError
from flinkml_tpu.cluster.process import WorkerProcess, WorkerSpec
from flinkml_tpu.serving.batcher import ServingRequest
from flinkml_tpu.serving.engine import (
    PendingPrediction,
    ServingConfig,
    ServingEngine,
    ServingResponse,
)
from flinkml_tpu.serving.errors import (
    EngineStoppedError,
    ServingOverloadError,
    ServingTimeoutError,
)
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import LatencyWindow, metrics

_log = get_logger("cluster.remote")

#: Grace added to a request's serving deadline to form its TRANSPORT
#: deadline: the worker enforces the serving timeout itself; the client
#: sweep only catches a worker that went silent.
TRANSPORT_GRACE_S = 2.0


class _RemoteBacklog:
    """The ``engine._batcher`` shim: queued-rows here means rows in
    flight to the worker — the same backlog signal the router balances
    and sheds on for in-process replicas."""

    def __init__(self, owner: "RemoteEngine"):
        self._owner = owner

    @property
    def queued_rows(self) -> int:
        return self._owner._outstanding_rows

    @property
    def queue_depth(self) -> int:
        return self._owner._outstanding_requests

    @property
    def max_queue_rows(self) -> int:
        return self._owner.config.max_queue_rows


class RemoteEngine:
    """See module docstring. Owns one :class:`WorkerProcess` and one
    :class:`WorkerClient`; ``start()`` spawns and connects."""

    def __init__(
        self,
        source: Any,
        example: Table,
        config: Optional[ServingConfig] = None,
        output_cols: Optional[Sequence[str]] = None,
        name: str = "remote",
        *,
        compile_cache_dir: Optional[str] = None,
        devices_per_worker: Optional[int] = 1,
        spawn_timeout_s: float = 180.0,
        worker_env: Optional[Mapping[str, str]] = None,
        transport_window: Optional[LatencyWindow] = None,
        cluster_metrics: Optional[Any] = None,
    ):
        import pickle

        from flinkml_tpu.serving.engine import _tuned_float, _tuned_int
        from flinkml_tpu.serving.registry import ModelRegistry

        cfg = config or ServingConfig()
        # Same knob resolution as ServingEngine: everything downstream
        # (client-side validation, admission) reads concrete values,
        # and the worker gets the SAME concrete values (both sides of
        # the wire must agree on max_batch_rows).
        self.config = dataclasses.replace(
            cfg,
            max_batch_rows=(
                int(cfg.max_batch_rows) if cfg.max_batch_rows is not None
                else _tuned_int("serving_max_batch_rows", 1024)
            ),
            max_wait_ms=(
                float(cfg.max_wait_ms) if cfg.max_wait_ms is not None
                else _tuned_float("serving_window_ms", 2.0)
            ),
        )
        self.name = name
        self._schema = {
            n: (np.asarray(example.column(n)).dtype,
                np.asarray(example.column(n)).shape[1:])
            for n in example.column_names
        }
        self._metrics = metrics.group(
            f"serving.{self.config.metrics_name or name}",
            labels=self.config.metrics_labels,
        )
        self._latency_window = LatencyWindow(
            self._metrics, self.config.latency_window
        )
        self._transport_window = transport_window
        self._cluster_metrics = cluster_metrics
        self._batcher = _RemoteBacklog(self)
        self._outstanding_rows = 0
        self._outstanding_requests = 0
        self._outstanding_lock = threading.Lock()
        self._active_version: Optional[int] = None
        self._started = False

        # The child's construction record. Engine-side knobs that are
        # process-local (device/mesh pins, metric labels) stay home;
        # the worker runs the queue/batching/precision knobs.
        wire_fields = (
            "max_batch_rows", "max_wait_ms", "max_queue_rows",
            "default_timeout_ms", "warmup_row_counts", "latency_window",
            "batching", "refuse_nonfinite", "precision",
            "hbm_budget_bytes",
        )
        worker_cfg = {
            f: getattr(self.config, f) for f in wire_fields
            if getattr(self.config, f) is not None
            or f in ("default_timeout_ms", "warmup_row_counts",
                     "precision", "hbm_budget_bytes")
        }
        # A worker IS the failover unit: it never sheds to its own
        # host path (mirrors ReplicaPool forcing shed_on_overload off).
        worker_cfg["shed_on_overload"] = False
        example_cols = {
            n: np.asarray(example.column(n)) for n in example.column_names
        }
        if isinstance(source, ModelRegistry):
            source_spec = {"kind": "registry", "root": source.root}
        else:
            try:
                source_spec = {
                    "kind": "model",
                    "blob": pickle.dumps(source, protocol=5),
                }
            except Exception:
                # Most fitted stages are not picklable (param
                # validators hold lambdas) — ship them through the
                # registry's own save/load machinery instead: publish
                # once to a private single-version registry root and
                # let the worker load it back as a FIXED model
                # (version=None responses, same as in-process).
                import tempfile

                root = tempfile.mkdtemp(
                    prefix=f"flinkml-remote-{name.replace('/', '-')}-"
                )
                ModelRegistry(root).publish(source)
                source_spec = {"kind": "fixed_via_registry",
                               "root": root}
        spec = WorkerSpec(
            example=example_cols,
            source=source_spec,
            config=worker_cfg,
            output_cols=tuple(output_cols) if output_cols else None,
            name=name, compile_cache_dir=compile_cache_dir,
        )
        self.process = WorkerProcess(
            spec, name=name, devices_per_worker=devices_per_worker,
            spawn_timeout_s=spawn_timeout_s, env=worker_env,
        )
        self.client: Optional[WorkerClient] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def running(self) -> bool:
        return (
            self._started
            and self.process.alive
            and self.client is not None
            and self.client.connected
        )

    @property
    def active_version(self) -> Optional[int]:
        return self._active_version

    @property
    def queued_rows(self) -> int:
        return self._outstanding_rows

    def observed_p99_ms(self) -> Optional[float]:
        snap = self._metrics.snapshot()
        return snap["gauges"].get("p99_ms")

    def start(self) -> "RemoteEngine":
        if self.running:
            return self
        if not self.process.alive:
            self.process.spawn()
            if self._cluster_metrics is not None:
                self._cluster_metrics.record(
                    "spawn_ms", float(self.process.spawn_ms or 0.0)
                )
        self.client = WorkerClient(
            self.process.host, self.process.port,
            max_payload=(self.process.spec.max_payload
                         or protocol.DEFAULT_MAX_PAYLOAD),
            metrics_group=self._cluster_metrics,
        ).connect()
        pong = self.client.call("ping", timeout_s=30.0)
        if not pong.get("ok"):
            raise WorkerDiedError(f"worker {self.name} failed its ping")
        self._started = True
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        self._started = False
        if self.client is not None and self.client.connected:
            try:
                self.client.call(
                    "shutdown", {"drain": drain},
                    timeout_s=min(timeout or 10.0, 10.0),
                )
            except (TransportError, OSError):
                pass  # already dying — the kill below settles it
        if self.client is not None:
            self.client.close()
        self.process.terminate()
        if self.process.join(timeout if timeout is not None else 10.0) \
                is None:
            self.process.kill()
            self.process.join(5.0)

    # -- request path ------------------------------------------------------
    # Borrowed verbatim: same schema table, same typed errors, zero
    # round trips for a malformed request.
    _normalize = ServingEngine._normalize

    def submit(
        self,
        features: Union[Table, Mapping[str, Any]],
        timeout_ms: Optional[float] = None,
    ) -> PendingPrediction:
        self._check_running()
        columns, rows = self._normalize(features)
        t0 = time.monotonic()
        timeout = (
            timeout_ms if timeout_ms is not None
            else self.config.default_timeout_ms
        )
        deadline = t0 + timeout / 1000.0 if timeout is not None else None
        with self._outstanding_lock:
            if (self._outstanding_rows + rows
                    > self.config.max_queue_rows):
                self._metrics.counter("rejected")
                raise ServingOverloadError(
                    f"worker {self.name} has "
                    f"{self._outstanding_rows} rows in flight "
                    f"(cap {self.config.max_queue_rows}); retry with "
                    "backoff"
                )
            self._outstanding_rows += rows
            self._outstanding_requests += 1
        self._metrics.counter("requests")
        self._metrics.counter("rows", float(rows))
        req = ServingRequest(
            columns=columns, rows=rows, enqueued_at=t0, deadline=deadline
        )

        def _on_done(result, error):
            with self._outstanding_lock:
                self._outstanding_rows -= rows
                self._outstanding_requests -= 1
            rtt_ms = (time.monotonic() - t0) * 1000.0
            if self._transport_window is not None:
                self._transport_window.record(rtt_ms)
            if error is not None:
                if isinstance(error, (ServingTimeoutError,
                                      TimeoutError)):
                    if req.claim_timeout_count():
                        self._metrics.counter("timeouts")
                    # Preserve the serving-typed shape for the router.
                    if not isinstance(error, ServingTimeoutError):
                        error = ServingTimeoutError(str(error))
                if req.fail(error):
                    self._metrics.counter("errors")
                return
            version = result.get("version")
            if version is not None:
                self._active_version = version
            if req.complete(result["columns"], version,
                            bool(result.get("shed"))):
                self._latency_window.record(rtt_ms)

        transport_deadline = (
            deadline + TRANSPORT_GRACE_S if deadline is not None else None
        )
        try:
            self.client.submit(
                "predict",
                {"columns": columns, "timeout_ms": timeout},
                deadline=transport_deadline, on_done=_on_done,
            )
        except TransportError:
            with self._outstanding_lock:
                self._outstanding_rows -= rows
                self._outstanding_requests -= 1
            raise
        return PendingPrediction(self, req, t0)

    def predict(
        self,
        features: Union[Table, Mapping[str, Any]],
        timeout_ms: Optional[float] = None,
    ) -> ServingResponse:
        pending = self.submit(features, timeout_ms=timeout_ms)
        req = pending.request
        remaining = (
            None if req.deadline is None
            else max(0.0, req.deadline - time.monotonic())
        )
        if not req.done.wait(
                None if remaining is None
                else remaining + TRANSPORT_GRACE_S + 0.25):
            if req.claim_timeout_count():
                self._metrics.counter("timeouts")
            raise ServingTimeoutError(
                f"request did not complete within {timeout_ms}ms"
            )
        return pending.response()

    # -- registry / control ------------------------------------------------
    def swap_to(self, version: Optional[int] = None) -> int:
        self._check_running()
        out = self.client.call(
            "swap_to", {"version": version}, timeout_s=120.0
        )
        self._active_version = out["version"]
        return out["version"]

    def worker_stats(self) -> Dict[str, Any]:
        """The worker's own stats snapshot (engine stats + fusion
        compile counters — the warm-scale-up audit)."""
        self._check_running()
        return self.client.call("stats", timeout_s=30.0)

    def stats(self) -> Dict[str, Any]:
        snap = self._metrics.snapshot()
        return {
            "name": self.name,
            "running": self.running,
            "active_version": self.active_version,
            "queue_depth": self._batcher.queue_depth,
            "queued_rows": self._batcher.queued_rows,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }

    def _check_running(self) -> None:
        if not self._started:
            raise EngineStoppedError(
                f"remote engine {self.name} is not started"
            )
        if not self.process.alive or self.client is None \
                or not self.client.connected:
            raise WorkerDiedError(
                f"worker {self.name} is down "
                f"(rc={self.process.returncode})"
            )
