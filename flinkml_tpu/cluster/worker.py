"""Worker child process: one ServingEngine behind the frame transport.

Run as ``python -m flinkml_tpu.cluster.worker <spec.pkl>``. The spec
(written by :class:`~flinkml_tpu.cluster.process.WorkerProcess`) names
the model source, the request schema example, the engine config, and —
critically — the shared compile-cache directory: the engine's warmup
routes through :mod:`flinkml_tpu.compile_cache`, so a worker joining a
pool whose siblings already compiled every (program, bucket, policy)
pays retarget-load I/O, not XLA compiles (time-to-first-prediction
stays I/O-bound — the PR 11 contract carried across a process
boundary).

Startup order:

1. pin env (``JAX_PLATFORMS``/``XLA_FLAGS`` come from the parent — the
   device slice this worker owns), configure the compile cache, then
   :func:`~flinkml_tpu.parallel.distributed.init_distributed` — a
   no-op single-process unless the parent exported the
   ``FLINKML_TPU_COORD_ADDR``-family rendezvous env;
2. build + start the engine (load, warmup);
3. bind ``127.0.0.1:0``, print ONE JSON ready line
   (``{"ready": true, "port": N, "pid": P, "spawn_stage_ms": ...}``)
   to stdout — the only thing a worker ever writes there; logs go to
   stderr;
4. serve request frames until ``shutdown`` (each connection gets its
   own reader thread; ops run on a small pool so one slow predict
   cannot starve ``ping``).

Every op answers with a RESPONSE frame or a typed ERROR frame
(:func:`~flinkml_tpu.cluster.errors.encode_error`); recognized serving
errors re-raise client-side as themselves, so the router's failover
table is process-transparent.

The ``cluster.worker`` fault seam fires before every predict dispatch
with ``{"worker", "request"}`` context — a scripted
:class:`~flinkml_tpu.faults.WorkerCrash` hard-exits the process
mid-traffic, which is how the chaos stages kill a real worker instead
of simulating one.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

OPS_THREADS = 8


def _find_embedding_table(model: Any):
    """The served model's embedding stage, if any: an
    :class:`~flinkml_tpu.embeddings.serving.EmbeddingLookupModel` (bare
    or inside a pipeline's stages) exposing host rows / a bound table."""
    stages = list(getattr(model, "stages", None) or [model])
    for stage in stages:
        if hasattr(stage, "_table") or hasattr(stage, "_rows"):
            return stage
    return None


class WorkerServer:
    """The in-process server; split from ``main`` so tests can run a
    worker inside a thread against scripted transports."""

    def __init__(self, engine: Any, *, name: str = "worker",
                 max_payload: Optional[int] = None):
        from flinkml_tpu.cluster import protocol

        self.engine = engine
        self.name = name
        self.max_payload = (
            int(max_payload) if max_payload
            else protocol.DEFAULT_MAX_PAYLOAD
        )
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._ops = ThreadPoolExecutor(
            max_workers=OPS_THREADS, thread_name_prefix=f"{name}-op"
        )
        self._predicts = 0
        self._count_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def bind(self, host: str = "127.0.0.1", port: int = 0) -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        sock.listen(8)
        self._listener = sock
        return sock.getsockname()[1]

    def serve_forever(self) -> None:
        assert self._listener is not None, "bind() first"
        self._listener.settimeout(0.25)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve_connection, args=(conn,),
                name=f"{self.name}-conn", daemon=True,
            ).start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self._ops.shutdown(wait=False)

    # -- connection loop ---------------------------------------------------
    def _serve_connection(self, conn: socket.socket) -> None:
        from flinkml_tpu.cluster import protocol
        from flinkml_tpu.cluster.errors import (
            ConnectionClosedError, TransportError,
        )

        send_lock = threading.Lock()
        try:
            while not self._stop.is_set():
                try:
                    frame = protocol.recv_frame(
                        conn, deadline=time.monotonic() + 1.0,
                        max_payload=self.max_payload,
                    )
                except protocol.TransportTimeoutError:
                    continue
                ftype, req_id, payload = frame
                if ftype != protocol.REQUEST:
                    continue
                self._ops.submit(
                    self._handle, conn, send_lock, req_id, payload
                )
        except ConnectionClosedError:
            pass
        except (TransportError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn: socket.socket, send_lock: threading.Lock,
                req_id: int, payload: Dict[str, Any]) -> None:
        from flinkml_tpu.cluster import protocol
        from flinkml_tpu.cluster.errors import encode_error

        op = str(payload.get("op", ""))
        try:
            result = self._dispatch(op, payload)
            ftype, body = protocol.RESPONSE, result
        except BaseException as e:  # noqa: BLE001 — typed over the wire
            ftype, body = protocol.ERROR, encode_error(e)
        try:
            with send_lock:
                protocol.send_frame(
                    conn, ftype, req_id, body, self.max_payload
                )
        except OSError:
            pass  # client went away; nothing to tell it

    # -- ops ---------------------------------------------------------------
    def _dispatch(self, op: str, p: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        from flinkml_tpu import faults
        from flinkml_tpu.cluster.errors import OversizedFrameError

        if op == "ping":
            return {"ok": True, "pid": os.getpid(), "worker": self.name}
        if op == "predict":
            with self._count_lock:
                self._predicts += 1
                n = self._predicts
            if faults.ACTIVE is not None:
                faults.fire("cluster.worker", worker=self.name, request=n)
            resp = self.engine.predict(
                p["columns"], timeout_ms=p.get("timeout_ms")
            )
            return {
                "columns": {
                    c: np.asarray(v) for c, v in resp.columns.items()
                },
                "version": resp.version,
                "shed": resp.shed,
            }
        if op == "stats":
            from flinkml_tpu.utils.metrics import metrics

            fusion = dict(
                metrics.group("pipeline.fusion").snapshot()["counters"]
            )
            return {
                "stats": self.engine.stats(),
                "fusion_counters": fusion,
                "pid": os.getpid(),
            }
        if op == "swap_to":
            return {"version": self.engine.swap_to(p.get("version"))}
        if op == "embedding_rows":
            table = _find_embedding_table(
                getattr(self.engine, "_active", None).model
                if getattr(self.engine, "_active", None) is not None
                else None
            )
            if table is None:
                raise ValueError(
                    "served model has no embedding stage to exchange "
                    "rows from"
                )
            ids = np.asarray(p["ids"], np.int64).ravel()
            rows_src = getattr(table, "_rows")
            vocab, dim = rows_src.shape
            want_bytes = int(ids.size) * int(dim) * rows_src.dtype.itemsize
            # DCN-aware shape: the exchange is batch-sized BY
            # CONSTRUCTION — a vocab-sized request is refused before a
            # row is gathered, same type the framing cap raises.
            budget = self.max_payload // 2
            if ids.size >= vocab or want_bytes > budget:
                raise OversizedFrameError(
                    f"embedding row request of {ids.size} ids "
                    f"({want_bytes} bytes) is not batch-sized "
                    f"(vocab {vocab}, payload budget {budget}); "
                    "exchange batch-sized id sets only"
                )
            if ids.size and (ids.min() < 0 or ids.max() >= vocab):
                raise ValueError(
                    f"embedding ids out of range [0, {vocab})"
                )
            bound = getattr(table, "_table", None)
            if bound is not None:
                rows = np.asarray(bound.lookup(ids.astype(np.int32)))
            else:
                rows = np.asarray(rows_src)[ids]
            return {"rows": rows, "dim": int(dim)}
        if op == "lease":
            return self._lease_op(p)
        if op == "arm_faults":
            from flinkml_tpu import faults as faults_mod

            faults_mod.arm(faults_mod.plan_from_json(p["plan_json"]))
            return {"ok": True, "faults": len(faults_mod.ACTIVE.faults)}
        if op == "crash":
            # Test/chaos hook: die NOW, mid-protocol — the client must
            # see WorkerDiedError, never a hang.
            os._exit(int(p.get("code", 11)))
        if op == "shutdown":
            drain = bool(p.get("drain", True))
            threading.Thread(
                target=self._stop_engine, args=(drain,), daemon=True
            ).start()
            return {"ok": True}
        raise ValueError(f"unknown worker op {op!r}")

    def _stop_engine(self, drain: bool) -> None:
        try:
            self.engine.stop(drain=drain, timeout=10.0)
        finally:
            self.shutdown()

    def _lease_op(self, p: Dict[str, Any]) -> Dict[str, Any]:
        """Cross-process lease reclaim: the PR 15 revoke→release
        handshake served over the transport. ``list`` exposes this
        process's active slice leases; ``request_revoke`` asks the
        holder to wind down; ``wait_released`` blocks (bounded) until
        the holder's own release lands. ``acquire``/``release`` exist
        so tests can stand up a real lease inside the worker."""
        from flinkml_tpu.parallel import dispatch as pdispatch

        cmd = str(p.get("cmd", "list"))
        if cmd == "list":
            return {
                "leases": [ls.snapshot() for ls in pdispatch.active_leases()]
            }
        if cmd == "acquire":
            import jax

            n = int(p.get("n", 1))
            ids = p.get("devices") or [d.id for d in jax.devices()[:n]]
            lease = pdispatch.lease_devices(
                ids, str(p.get("holder", "worker-trainer"))
            )
            if bool(p.get("cooperative", False)):
                # Stand in for a trainer honoring the revoke contract:
                # watch for request_revoke and release at the next safe
                # point (here: immediately) — the holder-side half the
                # cross-process reclaim handshake needs to complete.
                def _honor_revoke(ls=lease):
                    while ls.active:
                        if ls.revoke_requested():
                            ls.release()
                            return
                        time.sleep(0.05)

                threading.Thread(
                    target=_honor_revoke,
                    name=f"{self.name}-lease-holder", daemon=True,
                ).start()
            return {"token": lease.token, "devices": sorted(lease.devices)}
        token = str(p.get("token", ""))
        lease = next(
            (ls for ls in pdispatch.active_leases() if ls.token == token),
            None,
        )
        if cmd == "request_revoke":
            if lease is None:
                return {"found": False, "released": True}
            lease.request_revoke(str(p.get("reason", "remote reclaim")))
            return {"found": True, "released": False}
        if cmd == "release":
            if lease is not None:
                lease.release()
            return {"found": lease is not None, "released": True}
        if cmd == "wait_released":
            if lease is None:
                return {"found": False, "released": True}
            released = lease.wait_released(
                timeout=float(p.get("timeout_s", 5.0))
            )
            return {"found": True, "released": bool(released)}
        raise ValueError(f"unknown lease cmd {cmd!r}")


def build_engine_from_spec(spec: Dict[str, Any]):
    """Engine construction shared by ``main`` and in-thread test
    servers. The spec is the pickled dict WorkerSpec writes."""
    from flinkml_tpu.serving import ServingConfig, ServingEngine
    from flinkml_tpu.table import Table

    source_spec = spec["source"]
    kind = source_spec.get("kind")
    if kind == "registry":
        from flinkml_tpu.serving import ModelRegistry

        source = ModelRegistry(source_spec["root"])
    elif kind == "fixed_via_registry":
        # A fixed (registry-less) model shipped through the registry's
        # save/load machinery because it does not pickle: load it back
        # and serve it FIXED (version=None responses, exactly like the
        # in-process engine would).
        from flinkml_tpu.serving import ModelRegistry

        _, source = ModelRegistry(source_spec["root"]).get()
    else:
        source = pickle.loads(source_spec["blob"])
    config = ServingConfig(**(spec.get("config") or {}))
    example = Table(dict(spec["example"]))
    return ServingEngine(
        source, example, config,
        output_cols=spec.get("output_cols"),
        name=spec.get("name", "worker"),
    )


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m flinkml_tpu.cluster.worker <spec.pkl>",
              file=sys.stderr)
        return 2
    t0 = time.monotonic()
    with open(argv[0], "rb") as f:
        spec = pickle.load(f)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if spec.get("compile_cache_dir"):
        from flinkml_tpu.compile_cache import ENV_DIR_VAR

        os.environ[ENV_DIR_VAR] = spec["compile_cache_dir"]

    from flinkml_tpu import compile_cache
    from flinkml_tpu.parallel import init_distributed
    from flinkml_tpu.utils.logging import get_logger

    log = get_logger("cluster.worker")
    if spec.get("compile_cache_dir"):
        compile_cache.configure(spec["compile_cache_dir"])
    # Env-driven rendezvous (FLINKML_TPU_COORD_ADDR et al. — a no-op
    # single-process): world size = process count.
    rank, world = init_distributed()

    engine = build_engine_from_spec(spec)
    engine.start()

    server = WorkerServer(
        engine, name=spec.get("name", "worker"),
        max_payload=spec.get("max_payload"),
    )
    port = server.bind()
    # The ready line: the ONE stdout write, parsed by WorkerProcess.
    print(json.dumps({
        "ready": True, "port": port, "pid": os.getpid(),
        "rank": rank, "world": world,
        "spawn_stage_ms": round((time.monotonic() - t0) * 1000.0, 1),
    }), flush=True)
    log.info("worker %s serving on 127.0.0.1:%d (rank %d/%d)",
             spec.get("name", "worker"), port, rank, world)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
