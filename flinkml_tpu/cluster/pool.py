"""ClusterPool: a ReplicaPool whose replicas are worker PROCESSES.

Subclasses :class:`~flinkml_tpu.serving.pool.ReplicaPool` and overrides
exactly one seam — replica construction — so every pool behavior
(router balance, typed failover, gray-fail defense, health quarantine,
autoscaler hooks, rolling hot swap) is inherited, not reimplemented.
Each replica slot holds a :class:`~flinkml_tpu.cluster.remote
.RemoteEngine` fronting one spawned worker; on a CPU mesh each worker
owns its own XLA executor pool and its own GIL, which is what finally
lets "N replicas" add real capacity (the PR 15 honest limit, removed).

Warm spawn: every worker is pointed at one shared on-disk compile-cache
directory (created for the pool when none is configured). The first
worker to warm a (program, bucket, policy) persists the AOT artifact;
every later worker — including a respawn after a crash — retarget-loads
it, so scale-up and recovery pay artifact I/O, not XLA compiles.

Cross-process helpers live here too: :func:`reclaim_worker_leases`
(the PR 15 revoke→release handshake carried over the transport) and
:func:`fetch_embedding_rows` (batch-sized row exchange; a vocab-sized
request is refused with the framing cap's own typed error).

Metrics: ``cluster.<pool>`` publishes ``workers_alive``, ``spawn_ms``
(meter), transport ``p50_ms``/``p99_ms`` (round-trip latency window),
and ``reconnects_total`` — see ``docs/development/cluster.md``.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Mapping, Optional, Sequence, Union

import numpy as np

from flinkml_tpu.cluster.client import WorkerClient
from flinkml_tpu.cluster.remote import RemoteEngine
from flinkml_tpu.serving.engine import ServingConfig
from flinkml_tpu.serving.health import HealthPolicy, ReplicaHealth
from flinkml_tpu.serving.pool import Replica, ReplicaPool
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import LatencyWindow, metrics

_log = get_logger("cluster.pool")


class ClusterPool(ReplicaPool):
    """See module docstring.

    ``n_workers`` worker processes, each ``devices_per_worker`` virtual
    CPU devices (its own XLA world). ``worker_env`` adds/overrides env
    for every child — exporting the ``FLINKML_TPU_COORD_ADDR`` family
    here is how operator-launched workers join one rendezvous.
    """

    def __init__(
        self,
        source: Union[ModelRegistry, Any],
        example: Table,
        *,
        config: Optional[ServingConfig] = None,
        n_workers: int = 2,
        output_cols: Optional[Sequence[str]] = None,
        name: str = "cluster",
        health_policy: Optional[HealthPolicy] = None,
        grayfail: Optional[Any] = None,
        devices_per_worker: Optional[int] = 1,
        worker_env: Optional[Mapping[str, str]] = None,
        spawn_timeout_s: float = 180.0,
        compile_cache_dir: Optional[str] = None,
    ):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self._init_core(
            source, example, config=config, output_cols=output_cols,
            name=name, health_policy=health_policy,
            share_compiles=True, grayfail=grayfail,
        )
        self._devices_per_worker = devices_per_worker
        self._worker_env = dict(worker_env or {})
        self._spawn_timeout_s = float(spawn_timeout_s)
        # One shared DISK store for every worker (a memory-only store
        # cannot cross a process boundary): explicit arg, else the
        # configured env store, else a pool-owned tempdir.
        from flinkml_tpu.compile_cache import ENV_DIR_VAR

        self._compile_cache_dir = (
            compile_cache_dir
            or os.environ.get(ENV_DIR_VAR)
            or tempfile.mkdtemp(prefix=f"flinkml-cluster-{name}-cache-")
        )
        self.cluster_metrics = metrics.group(f"cluster.{name}")
        self._transport_window = LatencyWindow(self.cluster_metrics)
        for _ in range(int(n_workers)):
            self.replicas.append(self._make_replica({}, source))
        self._update_worker_gauge()

    # -- the one overridden seam ------------------------------------------
    def _make_replica(self, place: Dict[str, Any], source: Any,
                      model_id: Optional[str] = None) -> Replica:
        i = self._next_index
        self._next_index += 1
        rname = f"r{i}"
        import dataclasses

        cfg = dataclasses.replace(
            self._base_config,
            metrics_name=self.name,
            metrics_labels={"replica": rname},
            shed_on_overload=False,
        )
        engine = RemoteEngine(
            source, self._example, cfg,
            output_cols=self._output_cols,
            name=f"{self.name}/{rname}",
            compile_cache_dir=self._compile_cache_dir,
            devices_per_worker=self._devices_per_worker,
            spawn_timeout_s=self._spawn_timeout_s,
            worker_env=self._worker_env,
            transport_window=self._transport_window,
            cluster_metrics=self.cluster_metrics,
        )
        return Replica(
            name=rname, engine=engine,
            health=ReplicaHealth(rname, self._health_policy),
            device=None, mesh=None, model_id=model_id,
        )

    # -- placement: workers, not devices ----------------------------------
    def add_replica(self, device: Optional[Any] = None,
                    mesh: Optional[Any] = None,
                    source: Optional[Any] = None,
                    model_id: Optional[str] = None) -> Replica:
        """Grow the pool by one WORKER (spawn → warm via the shared
        artifact store → join rotation). ``device``/``mesh`` are
        ignored — a worker's placement is its own process env."""
        replica = self._make_replica(
            {}, source if source is not None else self._source,
            model_id=model_id,
        )
        if self._started:
            replica.engine.start()
        self._seed_ewma(replica)
        self.replicas.append(replica)
        self._metrics.counter("replicas_added")
        self._metrics.gauge("replicas", float(len(self.replicas)))
        self._update_health_gauge()
        self._update_worker_gauge()
        _log.info("cluster pool %s scaled UP: worker %s pid %s (now %d)",
                  self.name, replica.name, replica.engine.process.pid,
                  len(self.replicas))
        return replica

    def start(self) -> "ClusterPool":
        # Workers warm via the shared DISK store; the base class's
        # in-process ensure_store() is irrelevant across processes.
        for replica in list(self.replicas):
            replica.engine.start()
        self._started = True
        self._update_worker_gauge()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        super().stop(drain=drain, timeout=timeout)
        self._update_worker_gauge()

    def respawn_dead(self) -> List[Replica]:
        """Replace every retired (dead-worker) replica with a freshly
        spawned one: prune the corpses, spawn warm successors. The
        recovery idiom the cluster smoke exercises — a respawned worker
        rejoins with ZERO new XLA compiles because its warmup
        retarget-loads the shared artifacts its predecessor persisted."""
        pruned = self.prune_retired()
        replaced = [self.add_replica() for _ in pruned]
        self._update_worker_gauge()
        return replaced

    def workers_alive(self) -> int:
        return sum(
            1 for r in self.replicas
            if getattr(r.engine, "process", None) is not None
            and r.engine.process.alive
        )

    def _update_worker_gauge(self) -> None:
        self.cluster_metrics.gauge(
            "workers_alive", float(self.workers_alive())
        )

    def worker_clients(self) -> List[WorkerClient]:
        """The live transport clients (lease reclaim, embedding
        exchange, stats scraping)."""
        return [
            r.engine.client for r in self.replicas
            if isinstance(r.engine, RemoteEngine)
            and r.engine.client is not None and r.engine.client.connected
        ]


def reclaim_worker_leases(
    client: WorkerClient,
    device_ids: Optional[Sequence[int]] = None,
    timeout_s: float = 10.0,
    reason: str = "cross-process reclaim",
) -> List[Dict[str, Any]]:
    """The revoke→release handshake over the transport: list the
    worker's active slice leases (optionally only those overlapping
    ``device_ids``), request revocation of each, and wait — bounded —
    for the holders' own releases to land. Returns the final snapshots;
    a lease still unreleased at the deadline is returned with
    ``released: False`` so the caller can escalate (the stuck-worker
    runbook) instead of silently placing work on a contested slice."""
    leases = client.call("lease", {"cmd": "list"},
                         timeout_s=timeout_s)["leases"]
    if device_ids is not None:
        wanted = set(int(i) for i in device_ids)
        leases = [
            ls for ls in leases if wanted & set(ls["devices"])
        ]
    out = []
    for ls in leases:
        client.call("lease", {
            "cmd": "request_revoke", "token": ls["token"],
            "reason": reason,
        }, timeout_s=timeout_s)
        done = client.call("lease", {
            "cmd": "wait_released", "token": ls["token"],
            "timeout_s": timeout_s,
        }, timeout_s=timeout_s + 5.0)
        out.append({**ls, "released": bool(done["released"])})
    return out


def fetch_embedding_rows(
    client: WorkerClient,
    ids: Sequence[int],
    timeout_s: float = 30.0,
) -> np.ndarray:
    """Batch-sized embedding row exchange across the process boundary.
    The worker refuses anything vocab-sized (payload-cap typed error)
    — the DCN-aware shape of the PR 14 ICI-only exchange."""
    out = client.call(
        "embedding_rows", {"ids": np.asarray(ids, np.int64)},
        timeout_s=timeout_s,
    )
    return np.asarray(out["rows"])
