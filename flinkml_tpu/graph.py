"""Graph / GraphBuilder / GraphModel — DAGs of stages.

Parity with ``ml/builder/GraphBuilder.java:39-433``, ``Graph.java:54``,
``GraphModel.java:50``, ``GraphNode.java:33``, ``TableId.java:29``,
``GraphExecutionHelper.java:36-114``:

  - ``GraphBuilder`` records a DAG of stages connected by symbolic
    ``TableId``s (``create_table_id``, ``add_algo_operator``,
    ``add_estimator``, model-data wiring) and builds either a ``Graph``
    (an Estimator) or a ``GraphModel`` (a Model).
  - ``Graph.fit`` executes nodes in topological order: Estimator nodes are
    fit then used to transform; AlgoOperator nodes transform directly; the
    result is a ``GraphModel`` over the fitted stages.
  - Save/load mirrors the numbered-subdirectory layout with a JSON node list
    (``GraphData``-equivalent) in the metadata.

Execution is eager over in-memory ``Table``s (the reference's lazy Flink
Transformations exist for cluster deployment, not for the DAG semantics).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from flinkml_tpu.api import AlgoOperator, Estimator, Model, Stage
from flinkml_tpu.io import read_write
from flinkml_tpu.table import Table


class TableId:
    """Symbolic handle for a table to be produced at execution time.

    Parity: ``TableId.java:29``.
    """

    def __init__(self, table_id: int):
        self.id = int(table_id)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, TableId) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __repr__(self) -> str:  # pragma: no cover
        return f"TableId({self.id})"


class GraphNode:
    """One stage in the DAG plus its input/output TableIds.

    Parity: ``GraphNode.java:33`` (nodeId, stageType, estimatorInputIds,
    algoOpInputIds, outputIds, inputModelDataIds, outputModelDataIds).
    """

    ESTIMATOR = "ESTIMATOR"
    ALGO_OPERATOR = "ALGO_OPERATOR"

    def __init__(
        self,
        node_id: int,
        stage: Optional[Stage],
        stage_type: str,
        estimator_input_ids: Optional[Sequence[TableId]],
        algo_op_input_ids: Sequence[TableId],
        output_ids: Sequence[TableId],
        input_model_data_ids: Optional[Sequence[TableId]] = None,
        output_model_data_ids: Optional[Sequence[TableId]] = None,
    ):
        self.node_id = node_id
        self.stage = stage
        self.stage_type = stage_type
        self.estimator_input_ids = (
            list(estimator_input_ids) if estimator_input_ids is not None else None
        )
        self.algo_op_input_ids = list(algo_op_input_ids)
        self.output_ids = list(output_ids)
        self.input_model_data_ids = (
            list(input_model_data_ids) if input_model_data_ids is not None else None
        )
        self.output_model_data_ids = (
            list(output_model_data_ids) if output_model_data_ids is not None else None
        )

    # -- JSON --------------------------------------------------------------
    def to_map(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "nodeId": self.node_id,
            "stageType": self.stage_type,
            "algoOpInputIds": [t.id for t in self.algo_op_input_ids],
            "outputIds": [t.id for t in self.output_ids],
        }
        if self.estimator_input_ids is not None:
            out["estimatorInputIds"] = [t.id for t in self.estimator_input_ids]
        if self.input_model_data_ids is not None:
            out["inputModelDataIds"] = [t.id for t in self.input_model_data_ids]
        if self.output_model_data_ids is not None:
            out["outputModelDataIds"] = [t.id for t in self.output_model_data_ids]
        return out

    @staticmethod
    def from_map(m: Dict[str, Any]) -> "GraphNode":
        ids = lambda key: [TableId(i) for i in m[key]] if key in m else None
        return GraphNode(
            node_id=int(m["nodeId"]),
            stage=None,
            stage_type=m["stageType"],
            estimator_input_ids=ids("estimatorInputIds"),
            algo_op_input_ids=[TableId(i) for i in m["algoOpInputIds"]],
            output_ids=[TableId(i) for i in m["outputIds"]],
            input_model_data_ids=ids("inputModelDataIds"),
            output_model_data_ids=ids("outputModelDataIds"),
        )

    def all_input_ids(self) -> List[TableId]:
        out = list(self.algo_op_input_ids)
        if self.estimator_input_ids is not None:
            out += self.estimator_input_ids
        if self.input_model_data_ids is not None:
            out += self.input_model_data_ids
        return out


class _ExecutionContext:
    """Maps TableIds to concrete Tables, executing nodes as they become ready.

    Parity: ``GraphExecutionHelper.java:36-114`` (topological execution of
    ready nodes).
    """

    def __init__(self) -> None:
        self.tables: Dict[TableId, Table] = {}

    def set_tables(self, ids: Sequence[TableId], tables: Sequence[Table]) -> None:
        # A node may declare more output slots than the stage actually
        # produces (max_output_table_num); extra slots stay unassigned. The
        # reverse — more tables than slots — is a misconfiguration.
        if len(tables) > len(ids):
            raise ValueError(
                f"stage produced {len(tables)} tables but only {len(ids)} "
                "output slots are allocated; raise set_max_output_table_num"
            )
        for tid, tbl in zip(ids, tables):
            self.tables[tid] = tbl

    def get_tables(self, ids: Sequence[TableId]) -> Tuple[Table, ...]:
        return tuple(self.tables[tid] for tid in ids)

    def ready(self, node: GraphNode) -> bool:
        return all(tid in self.tables for tid in node.all_input_ids())


def _execute_nodes(
    nodes: Sequence[GraphNode], ctx: _ExecutionContext, fit_mode: bool
) -> List[GraphNode]:
    """Run the DAG; returns fitted model-nodes (Graph.java:81-135 semantics)."""
    pending = list(nodes)
    model_nodes: List[GraphNode] = []
    while pending:
        node = next((n for n in pending if ctx.ready(n)), None)
        if node is None:
            raise ValueError(
                "Graph is not executable: some node inputs are never produced "
                "(cycle or missing input table)"
            )
        pending.remove(node)
        stage = node.stage
        if fit_mode and node.stage_type == GraphNode.ESTIMATOR:
            stage = stage.fit(*ctx.get_tables(node.estimator_input_ids))
        if node.input_model_data_ids is not None:
            stage.set_model_data(*ctx.get_tables(node.input_model_data_ids))
        outputs = stage.transform(*ctx.get_tables(node.algo_op_input_ids))
        ctx.set_tables(node.output_ids, outputs)
        if node.output_model_data_ids is not None:
            ctx.set_tables(node.output_model_data_ids, stage.get_model_data())
        model_nodes.append(
            GraphNode(
                node.node_id,
                stage,
                GraphNode.ALGO_OPERATOR,
                None,
                node.algo_op_input_ids,
                node.output_ids,
                node.input_model_data_ids,
                node.output_model_data_ids,
            )
        )
    return model_nodes


class GraphBuilder:
    """Records stages wired by TableIds; builds Graph/GraphModel.

    Parity: ``GraphBuilder.java:39-433``. Because a stage's output arity is
    unknown until execution, each added stage is given
    ``max_output_table_num`` symbolic outputs (``setMaxOutputTableNum``,
    GraphBuilder.java:61); unused slots are simply never materialized.
    """

    def __init__(self) -> None:
        self._next_table_id = 0
        self._next_node_id = 0
        self._max_output_table_num = 20
        self._nodes: List[GraphNode] = []
        # stage identity → node, for model-data wiring after the fact.
        self._stage_nodes: Dict[int, GraphNode] = {}

    def set_max_output_table_num(self, n: int) -> "GraphBuilder":
        self._max_output_table_num = n
        return self

    def create_table_id(self) -> TableId:
        tid = TableId(self._next_table_id)
        self._next_table_id += 1
        return tid

    def _new_output_ids(self) -> List[TableId]:
        return [self.create_table_id() for _ in range(self._max_output_table_num)]

    def _add_node(self, node: GraphNode, stage: Stage) -> None:
        self._nodes.append(node)
        self._stage_nodes[id(stage)] = node

    def add_algo_operator(self, algo_op: AlgoOperator, *inputs: TableId) -> List[TableId]:
        """Parity: GraphBuilder.addAlgoOperator (:98-122)."""
        outputs = self._new_output_ids()
        node = GraphNode(
            self._next_node_id, algo_op, GraphNode.ALGO_OPERATOR, None, list(inputs), outputs
        )
        self._next_node_id += 1
        self._add_node(node, algo_op)
        return outputs

    def add_estimator(
        self,
        estimator: Estimator,
        *inputs: TableId,
        estimator_inputs: Optional[Sequence[TableId]] = None,
        model_inputs: Optional[Sequence[TableId]] = None,
    ) -> List[TableId]:
        """Parity: GraphBuilder.addEstimator (:124-167).

        With only ``*inputs``, the fitted model transforms the same tables
        the estimator was fit on; ``estimator_inputs``/``model_inputs`` split
        them when they differ.
        """
        if estimator_inputs is None:
            estimator_inputs = list(inputs)
        if model_inputs is None:
            model_inputs = list(inputs)
        outputs = self._new_output_ids()
        node = GraphNode(
            self._next_node_id,
            estimator,
            GraphNode.ESTIMATOR,
            list(estimator_inputs),
            list(model_inputs),
            outputs,
        )
        self._next_node_id += 1
        self._add_node(node, estimator)
        return outputs

    def set_model_data_on_estimator(self, estimator: Estimator, *inputs: TableId) -> None:
        """Parity: GraphBuilder.setModelDataOnEstimator (:169-193)."""
        self._node_of(estimator).input_model_data_ids = list(inputs)

    def set_model_data_on_model(self, model: Model, *inputs: TableId) -> None:
        """Parity: GraphBuilder.setModelDataOnModel (:195-224)."""
        self._node_of(model).input_model_data_ids = list(inputs)

    def get_model_data_from_estimator(self, estimator: Estimator) -> List[TableId]:
        """Parity: GraphBuilder.getModelDataFromEstimator (:226-255)."""
        node = self._node_of(estimator)
        node.output_model_data_ids = self._new_output_ids()
        return node.output_model_data_ids

    def get_model_data_from_model(self, model: Model) -> List[TableId]:
        """Parity: GraphBuilder.getModelDataFromModel (:257-284)."""
        node = self._node_of(model)
        node.output_model_data_ids = self._new_output_ids()
        return node.output_model_data_ids

    def _node_of(self, stage: Stage) -> GraphNode:
        node = self._stage_nodes.get(id(stage))
        if node is None:
            raise ValueError(f"Stage {stage!r} has not been added to this GraphBuilder")
        return node

    # -- builders ----------------------------------------------------------
    def build_estimator(
        self,
        inputs: Sequence[TableId],
        outputs: Sequence[TableId],
        input_model_data: Optional[Sequence[TableId]] = None,
        output_model_data: Optional[Sequence[TableId]] = None,
        model_inputs: Optional[Sequence[TableId]] = None,
    ) -> "Graph":
        """Parity: GraphBuilder.buildEstimator (:286-357)."""
        return Graph(
            list(self._nodes),
            list(inputs),
            list(model_inputs if model_inputs is not None else inputs),
            list(outputs),
            list(input_model_data) if input_model_data is not None else None,
            list(output_model_data) if output_model_data is not None else None,
        )

    def build_algo_operator(
        self, inputs: Sequence[TableId], outputs: Sequence[TableId]
    ) -> "GraphModel":
        """Parity: GraphBuilder.buildAlgoOperator (:359-374)."""
        return self.build_model(inputs, outputs)

    def build_model(
        self,
        inputs: Sequence[TableId],
        outputs: Sequence[TableId],
        input_model_data: Optional[Sequence[TableId]] = None,
        output_model_data: Optional[Sequence[TableId]] = None,
    ) -> "GraphModel":
        """Parity: GraphBuilder.buildModel (:376-433)."""
        for node in self._nodes:
            if node.stage_type == GraphNode.ESTIMATOR:
                raise ValueError(
                    "build_model requires a DAG without Estimator-typed nodes"
                )
        return GraphModel(
            list(self._nodes),
            list(inputs),
            list(outputs),
            list(input_model_data) if input_model_data is not None else None,
            list(output_model_data) if output_model_data is not None else None,
        )


class Graph(Estimator):
    """An Estimator over a DAG of stages. Parity: ``Graph.java:54-135``."""

    def __init__(
        self,
        nodes: List[GraphNode],
        estimator_input_ids: List[TableId],
        model_input_ids: List[TableId],
        output_ids: List[TableId],
        input_model_data_ids: Optional[List[TableId]],
        output_model_data_ids: Optional[List[TableId]],
    ):
        super().__init__()
        self._nodes = nodes
        self._estimator_input_ids = estimator_input_ids
        self._model_input_ids = model_input_ids
        self._output_ids = output_ids
        self._input_model_data_ids = input_model_data_ids
        self._output_model_data_ids = output_model_data_ids

    def fit(self, *inputs: Table) -> "GraphModel":
        if len(inputs) != len(self._estimator_input_ids):
            raise ValueError(
                f"number of provided tables {len(inputs)} does not match the "
                f"expected number of tables {len(self._estimator_input_ids)}"
            )
        ctx = _ExecutionContext()
        ctx.set_tables(self._estimator_input_ids, inputs)
        model_nodes = _execute_nodes(self._nodes, ctx, fit_mode=True)
        gm = GraphModel(
            model_nodes,
            self._model_input_ids,
            self._output_ids,
            self._input_model_data_ids,
            self._output_model_data_ids,
        )
        gm._capture_model_data(ctx)
        return gm

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        _save_graph(self, path, self._nodes, self._graph_meta())

    def _graph_meta(self) -> Dict[str, Any]:
        return {
            "nodes": [n.to_map() for n in self._nodes],
            "estimatorInputIds": [t.id for t in self._estimator_input_ids],
            "modelInputIds": [t.id for t in self._model_input_ids],
            "outputIds": [t.id for t in self._output_ids],
            "inputModelDataIds": [t.id for t in self._input_model_data_ids]
            if self._input_model_data_ids is not None
            else None,
            "outputModelDataIds": [t.id for t in self._output_model_data_ids]
            if self._output_model_data_ids is not None
            else None,
        }

    @classmethod
    def load(cls, path: str) -> "Graph":
        meta = read_write.load_metadata(path)
        g = meta["graphData"]
        nodes = _load_graph_nodes(path, g)
        opt = lambda key: (
            [TableId(i) for i in g[key]] if g.get(key) is not None else None
        )
        return cls(
            nodes,
            [TableId(i) for i in g["estimatorInputIds"]],
            [TableId(i) for i in g["modelInputIds"]],
            [TableId(i) for i in g["outputIds"]],
            opt("inputModelDataIds"),
            opt("outputModelDataIds"),
        )


class GraphModel(Model):
    """A Model over a DAG of fitted stages. Parity: ``GraphModel.java:50``."""

    def __init__(
        self,
        nodes: List[GraphNode],
        input_ids: List[TableId],
        output_ids: List[TableId],
        input_model_data_ids: Optional[List[TableId]],
        output_model_data_ids: Optional[List[TableId]],
    ):
        super().__init__()
        self._nodes = nodes
        self._input_ids = input_ids
        self._output_ids = output_ids
        self._input_model_data_ids = input_model_data_ids
        self._output_model_data_ids = output_model_data_ids
        self._pending_model_data: Optional[Tuple[Table, ...]] = None
        self._model_data_tables: Optional[List[Table]] = None

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        if len(inputs) != len(self._input_ids):
            raise ValueError(
                f"number of provided tables {len(inputs)} does not match the "
                f"expected number of tables {len(self._input_ids)}"
            )
        ctx = _ExecutionContext()
        ctx.set_tables(self._input_ids, inputs)
        if self._input_model_data_ids is not None:
            if self._pending_model_data is None:
                raise ValueError(
                    "This GraphModel requires model data; call set_model_data "
                    "before transform"
                )
            ctx.set_tables(self._input_model_data_ids, self._pending_model_data)
        _execute_nodes(self._nodes, ctx, fit_mode=False)
        self._capture_model_data(ctx)
        return ctx.get_tables(self._output_ids)

    def set_model_data(self, *inputs: Table) -> "GraphModel":
        if self._input_model_data_ids is None:
            raise ValueError("This GraphModel does not accept external model data")
        if len(inputs) != len(self._input_model_data_ids):
            raise ValueError(
                f"number of provided model-data tables {len(inputs)} does not "
                f"match the expected number {len(self._input_model_data_ids)}"
            )
        self._pending_model_data = tuple(inputs)
        return self

    def get_model_data(self) -> List[Table]:
        """Exactly the tables wired via ``output_model_data`` at build time.

        Parity: ``GraphModel.java`` getModelData returns the tables at
        ``outputModelDataIds``; unwired graphs raise.
        """
        if self._output_model_data_ids is None:
            raise ValueError("This GraphModel exposes no model data")
        if self._model_data_tables is None:
            raise ValueError(
                "Model data is not available before fit/transform has executed"
            )
        return list(self._model_data_tables)

    def _capture_model_data(self, ctx: _ExecutionContext) -> None:
        if self._output_model_data_ids is None:
            return
        if all(tid in ctx.tables for tid in self._output_model_data_ids):
            self._model_data_tables = [
                ctx.tables[tid] for tid in self._output_model_data_ids
            ]

    def save(self, path: str) -> None:
        meta = {
            "nodes": [n.to_map() for n in self._nodes],
            "inputIds": [t.id for t in self._input_ids],
            "outputIds": [t.id for t in self._output_ids],
            "inputModelDataIds": [t.id for t in self._input_model_data_ids]
            if self._input_model_data_ids is not None
            else None,
            "outputModelDataIds": [t.id for t in self._output_model_data_ids]
            if self._output_model_data_ids is not None
            else None,
        }
        _save_graph(self, path, self._nodes, meta)

    @classmethod
    def load(cls, path: str) -> "GraphModel":
        meta = read_write.load_metadata(path)
        g = meta["graphData"]
        nodes = _load_graph_nodes(path, g)
        opt = lambda key: (
            [TableId(i) for i in g[key]] if g.get(key) is not None else None
        )
        return cls(
            nodes,
            [TableId(i) for i in g["inputIds"]],
            [TableId(i) for i in g["outputIds"]],
            opt("inputModelDataIds"),
            opt("outputModelDataIds"),
        )


def _save_graph(composite: Stage, path: str, nodes: Sequence[GraphNode], graph_meta: Dict) -> None:
    read_write.save_metadata(composite, path, extra={"graphData": graph_meta})
    for i, node in enumerate(nodes):
        node.stage.save(read_write.stage_path(path, i))


def _load_graph_nodes(path: str, graph_meta: Dict) -> List[GraphNode]:
    nodes = [GraphNode.from_map(m) for m in graph_meta["nodes"]]
    for i, node in enumerate(nodes):
        node.stage = read_write.load_stage(read_write.stage_path(path, i))
    return nodes
