"""flinkml_tpu — a TPU-native ML pipeline framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of Apache Flink ML
(reference: JingsongLi/flink-ml): a scikit-learn-style Estimator/Transformer/
Model/Pipeline API with typed validated params and JSON save/load, an
epoch-synchronized iteration runtime with termination criteria and mid-training
checkpoint/resume (bounded and unbounded/online modes), distributed primitives
(AllReduce via ``jax.lax.psum`` over ICI, broadcast model replication, keyed
aggregation via segment-sum, mapPartition-style per-shard compute), and an
algorithm library.

Design stance (see SURVEY.md §7): the reference spends ~10k LoC making a
dataflow engine loop (head/tail/feedback/alignment). On TPU the loop is the
program — a host loop (or ``lax.fori_loop``) around one jitted SPMD step —
and epoch alignment is implicit in SPMD lockstep. We keep the reference's API
surface and semantic guarantees, and discard its mechanism.
"""

from flinkml_tpu.params import (
    Param,
    IntParam,
    LongParam,
    FloatParam,
    BoolParam,
    StringParam,
    IntArrayParam,
    FloatArrayParam,
    StringArrayParam,
    ParamValidators,
    WithParams,
)
from flinkml_tpu.api import (
    Stage,
    AlgoOperator,
    Transformer,
    Model,
    Estimator,
)
from flinkml_tpu.table import Table
from flinkml_tpu.pipeline import Pipeline, PipelineModel
from flinkml_tpu.graph import GraphBuilder, Graph, GraphModel, TableId
from flinkml_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)

__version__ = "0.2.0"

__all__ = [
    "Param",
    "IntParam",
    "LongParam",
    "FloatParam",
    "BoolParam",
    "StringParam",
    "IntArrayParam",
    "FloatArrayParam",
    "StringArrayParam",
    "ParamValidators",
    "WithParams",
    "Stage",
    "AlgoOperator",
    "Transformer",
    "Model",
    "Estimator",
    "Table",
    "Pipeline",
    "PipelineModel",
    "GraphBuilder",
    "Graph",
    "GraphModel",
    "TableId",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
    "__version__",
]
