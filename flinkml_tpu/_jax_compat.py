"""Compatibility shims for the range of jax versions this package runs on.

The codebase targets the modern public surface (``jax.shard_map``,
``jax.distributed.is_initialized``); older jax releases (<= 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` and expose the distributed client
state privately. Installing the missing attributes once keeps every call
site on the modern spelling with no per-module guards.

Imported by the jax-facing modules (``parallel/mesh.py``,
``parallel/distributed.py`` and the direct consumers of the newer APIs) —
NOT by the package root, so ``import flinkml_tpu`` stays jax-free and user
code can still set ``JAX_PLATFORMS``/``XLA_FLAGS`` after importing the
package but before first device use. Installation is idempotent.

Import side effects only — this module defines nothing for callers.
"""

from __future__ import annotations

import jax


def _install() -> None:
    if not hasattr(jax, "shard_map"):
        import functools

        from jax.experimental.shard_map import shard_map

        # The experimental version's replication checker predates rules for
        # several primitives the modern one handles (e.g. `while`); modern
        # call sites expect those to just work, so the check defaults off.
        @functools.wraps(shard_map)
        def _shard_map(f, **kwargs):
            kwargs.setdefault("check_rep", False)
            return shard_map(f, **kwargs)

        jax.shard_map = _shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # psum of a Python scalar constant-folds to the static axis
            # size (never a tracer) on every jax this shim targets.
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):
        # Replication-tracking cast (replicated <-> device-varying). Older
        # jax has no varying-manual-axes machinery, and shard_map runs with
        # check_rep=False there (see above), so the cast is a no-op.
        def pcast(x, axis_name, *, to):
            del axis_name, to
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax, "typeof"):
        class _AvalView:
            """jax.typeof result shim: the underlying aval plus the modern
            ``.vma`` (varying-manual-axes) attribute, which is always empty
            here — consistent with pcast being a no-op."""

            __slots__ = ("_aval",)
            vma = frozenset()

            def __init__(self, aval):
                self._aval = aval

            def __getattr__(self, name):
                return getattr(self._aval, name)

        def typeof(x):
            import jax.core

            return _AvalView(jax.core.get_aval(x))

        jax.typeof = typeof

    if not hasattr(jax.distributed, "is_initialized"):
        def is_initialized() -> bool:
            from jax._src import distributed

            return getattr(distributed.global_state, "client", None) is not None

        jax.distributed.is_initialized = is_initialized


_install()
