"""Persistent AOT compile cache — zero-cold-start execution.

Every process today pays full trace+compile on spin-up even though the
programs it builds are identified by frozen, hashable, collision-tested
cache keys (the fused executor's chain/bucket/policy key, the serving
warmup's per-bucket keys, the plan-sharded step's lru key). This package
turns those identities into *persistent artifacts*: a compiled XLA
executable is serialized once (``jax.experimental.serialize_executable``,
the AOT half of ``jax.export``) and every later process — a fresh
replica, a rolling swap, an elastic reshard restart — loads it from disk
instead of recompiling, so time-to-first-prediction is I/O-bound.

See :mod:`flinkml_tpu.compile_cache.store` for the key schema,
invalidation rules, and the fallback ladder, and
``docs/development/compile_cache.md`` for the operator runbook.
"""

from flinkml_tpu.compile_cache.store import (  # noqa: F401
    CompileCacheStore,
    ENV_DIR_VAR,
    active_store,
    configure,
    ensure_store,
    env_fingerprint,
    reset,
    serialization_supported,
    stable_key_repr,
)

__all__ = [
    "CompileCacheStore",
    "ENV_DIR_VAR",
    "active_store",
    "configure",
    "ensure_store",
    "env_fingerprint",
    "reset",
    "serialization_supported",
    "stable_key_repr",
]
