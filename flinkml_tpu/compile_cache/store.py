"""The on-disk AOT artifact store behind :mod:`flinkml_tpu.compile_cache`.

Key schema
----------

An artifact is addressed by TWO fingerprints:

1. The **program key** — whatever hashable identity the compile site
   already uses for its in-memory cache (the fused executor's ``(chain
   fingerprint, ext specs, const specs, outputs, bucket, policy,
   kernel backend)`` tuple; the plan step's ``(mesh topology, plan,
   hypers, policy, shapes)``), rendered canonically by
   :func:`stable_key_repr` and
   hashed. The keys were built hashable and collision-tested for the
   in-memory caches; this module only adds persistence.
2. The **environment fingerprint** — jax/jaxlib version, backend
   platform, device kind, device count, PJRT platform version, and the
   ambient x64 flag (:func:`env_fingerprint`). A serialized executable
   is machine code for one runtime; a jax upgrade, a backend switch, or
   a different device kind MUST miss, never load a stale executable.

On disk: ``<dir>/<env_hash>/<key_hash>.aot`` (plus ``ENV.json``
describing the environment, for operators). One file per artifact; the
entry embeds its own env dict and a payload sha256, so a copied-in or
bit-rotted file is refused at read time even if it lands in the right
directory.

Invalidation rules
------------------

- env mismatch (different ``env_hash``, or an embedded env dict that
  disagrees at read time) → **miss** (counted ``env_mismatches``);
- torn/corrupt entry (unpicklable, wrong format, sha mismatch) →
  **miss**, logged loudly, the entry is deleted, and the caller's fresh
  compile rewrites it (counted ``corrupt_entries``) — never a crash;
- serialization unsupported (older jax, or a backend whose executables
  refuse ``serialize``) → the store degrades to compile-only, logged
  loudly ONCE (counted ``fallbacks``): behavior is exactly the
  in-memory jit path.

Concurrency: entries are written to a temp file in the cache directory
and published with ``os.replace`` (the ``CheckpointManager`` idiom), so
concurrent writers — N replicas, N processes — cannot tear each other;
last writer wins with an identical artifact. In-process, a per-key lock
makes racing compilers share ONE build (the replica-pool spin-up fix:
N replicas pay one compile, N-1 artifact loads).

Device retargeting: single-device artifacts record the device ids they
were compiled for and are re-loaded onto a DIFFERENT device by remapping
the device assignment at deserialize time — one artifact serves every
replica of a pool. Multi-device (SPMD) artifacts load only onto the same
device set; a different set is a miss (the program's collective schedule
is placement-specific).

Metrics (``metrics.group("compile_cache")``): ``hits`` / ``misses`` /
``stores`` / ``corrupt_entries`` / ``env_mismatches`` / ``fallbacks`` /
``retarget_loads`` counters and ``load_ms`` / ``compile_ms`` gauges
(last observed; full series under the same-named histories).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("compile_cache")

#: Setting this env var to a directory path activates a process-wide
#: disk-backed store lazily (no code changes at the compile sites).
ENV_DIR_VAR = "FLINKML_TPU_COMPILE_CACHE"

_FORMAT = 1

_SUPPORT = [None]  # tri-state probe cache: None unknown, True/False known
_WARNED_UNSUPPORTED = [False]


def serialization_supported() -> bool:
    """Whether this jax build exposes the AOT executable serialization
    API (``jax.experimental.serialize_executable``). Probed once; a
    False answer downgrades every store to compile-only with one loud
    log line (the in-memory jit behavior, unchanged)."""
    if _SUPPORT[0] is None:
        try:
            from jax.experimental import serialize_executable as se

            _SUPPORT[0] = callable(getattr(se, "serialize", None)) and \
                callable(getattr(se, "deserialize_and_load", None))
        except Exception:  # noqa: BLE001 — any import failure = unsupported
            _SUPPORT[0] = False
        if not _SUPPORT[0] and not _WARNED_UNSUPPORTED[0]:
            _WARNED_UNSUPPORTED[0] = True
            _log.warning(
                "jax.experimental.serialize_executable unavailable in this "
                "jax build; the compile cache degrades to in-memory jit "
                "(every process pays its own compiles)"
            )
    return bool(_SUPPORT[0])


def env_fingerprint() -> Dict[str, str]:
    """The environment half of the artifact key (see module docstring).
    Everything that can change what machine code a compile produces —
    or whether the produced code can legally load."""
    import jax
    import jaxlib

    devs = jax.devices()
    client = devs[0].client
    return {
        "jax": str(jax.__version__),
        "jaxlib": str(jaxlib.__version__),
        "backend": str(jax.default_backend()),
        "device_kind": str(devs[0].device_kind),
        "num_devices": str(len(devs)),
        "platform_version": str(getattr(client, "platform_version", "")),
        "x64": str(bool(jax.config.jax_enable_x64)),
    }


def _env_hash(env: Dict[str, str]) -> str:
    blob = "\x00".join(f"{k}={env[k]}" for k in sorted(env))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def stable_key_repr(key: Any) -> str:
    """A canonical, process-independent rendering of a cache key.

    ``repr`` of a tuple of primitives is already stable, but keys embed
    frozen dataclasses (``ShardingPlan``, ``PrecisionPolicy``) and may
    embed dicts; this renders dataclasses as sorted ``(field, value)``
    pairs and dicts sorted by key, so two processes building the same
    identity always hash to the same artifact."""
    out: list = []

    def walk(v: Any) -> str:
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            fields = sorted(
                (f.name, getattr(v, f.name)) for f in dataclasses.fields(v)
            )
            inner = ",".join(f"{n}={walk(x)}" for n, x in fields)
            return f"{type(v).__name__}({inner})"
        if isinstance(v, dict):
            inner = ",".join(
                f"{walk(k)}:{walk(v[k])}" for k in sorted(v, key=repr)
            )
            return f"{{{inner}}}"
        if isinstance(v, (tuple, list)):
            return "(" + ",".join(walk(x) for x in v) + ")"
        if isinstance(v, (str, bytes, int, float, bool)) or v is None:
            return repr(v)
        return f"{type(v).__name__}:{v!r}"

    out.append(walk(key))
    return "".join(out)


def _key_hash(key: Any) -> str:
    return hashlib.sha256(stable_key_repr(key).encode()).hexdigest()[:24]


class _RemapUnpickler(pickle.Unpickler):
    """``serialize_executable``'s unpickler with the device ids remapped:
    the payload's persistent ids carry ``('device', id)`` markers and the
    PJRT executable blob, and PJRT's ``deserialize_executable`` accepts a
    replacement device assignment — so ONE single-device artifact loads
    onto ANY device of the same kind (the pool's one-compile-per-N-
    replicas fix). Falls back to a fresh compile on any failure."""

    def __init__(self, file, backend, device_map: Dict[int, int]):
        super().__init__(file)
        self._backend = backend
        self._map = device_map
        self._by_id = {d.id: d for d in backend.devices()}

    def persistent_load(self, pid):
        import numpy as np

        from jax._src.lib import xla_client as xc

        if pid[0] == "exec":
            ids = [self._map[i] for i in sorted(self._map)]
            opts = xc.CompileOptions()
            opts.device_assignment = xc.DeviceAssignment.create(
                np.asarray([[i] for i in ids], dtype=np.int32)
            )
            return self._backend.deserialize_executable(pid[1], opts)
        if pid[0] == "device":
            return self._by_id[self._map.get(pid[1], pid[1])]
        if pid[0] == "client":
            return self._backend
        raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")


class CompileCacheStore:
    """Disk-backed (or memory-only) AOT artifact store.

    ``directory=None`` is a process-local store: artifacts live in
    memory only — no persistence, but N replicas in one process still
    share one compile. With a directory, artifacts additionally persist
    under ``<directory>/<env_hash>/`` and a FRESH process's compile
    sites become disk reads.
    """

    def __init__(self, directory: Optional[str] = None):
        self.directory = os.path.abspath(directory) if directory else None
        self._metrics = metrics.group("compile_cache")
        self._lock = threading.Lock()
        self._key_locks: Dict[str, threading.Lock] = {}
        # key hash -> entry dict (payload + trees + device ids). For a
        # MEMORY-ONLY store this is the storage itself (what lets pool
        # replicas share one compile without a cache directory); a
        # disk-backed store leaves it empty and re-reads entries from
        # disk per consumer, so executable bytes are never pinned in
        # RAM twice (call sites cache the loaded programs).
        self._memory: Dict[str, Dict[str, Any]] = {}
        self._env: Optional[Dict[str, str]] = None

    # -- plumbing ----------------------------------------------------------
    def _environment(self) -> Dict[str, str]:
        if self._env is None:
            self._env = env_fingerprint()
        return self._env

    def _key_lock(self, khash: str) -> threading.Lock:
        with self._lock:
            lock = self._key_locks.get(khash)
            if lock is None:
                lock = self._key_locks[khash] = threading.Lock()
            return lock

    def drop_memory(self) -> None:
        """Drop the in-process artifact layer (compile-counting tests
        want a clean slate); on-disk artifacts survive."""
        with self._lock:
            self._memory.clear()

    def entry_path(self, key: Any) -> Optional[str]:
        """The on-disk path ``key``'s artifact lives at (None for a
        memory-only store). Exists only after a successful store."""
        if self.directory is None:
            return None
        env_dir = os.path.join(self.directory,
                               _env_hash(self._environment()))
        return os.path.join(env_dir, f"{_key_hash(key)}.aot")

    # -- serialize / deserialize -------------------------------------------
    def _serialize(self, compiled, key: Any,
                   device_ids: Sequence[int]) -> Optional[Dict[str, Any]]:
        from jax.experimental import serialize_executable as se

        try:
            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as e:  # noqa: BLE001 — degrade, never crash
            self._metrics.counter("fallbacks")
            _log.warning(
                "AOT serialization failed for %s (%s: %s); this program "
                "stays in-memory only",
                stable_key_repr(key)[:120], type(e).__name__, e,
            )
            return None
        return {
            "format": _FORMAT,
            "env": dict(self._environment()),
            "key": stable_key_repr(key),
            "device_ids": [int(i) for i in device_ids],
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
            "sha256": hashlib.sha256(payload).hexdigest(),
        }

    def _load_entry(self, entry: Dict[str, Any],
                    device_ids: Optional[Sequence[int]]):
        """Deserialize an artifact entry into a callable
        ``jax.stages.Compiled``, retargeting single-device programs onto
        ``device_ids`` when they differ from the recorded ids. Returns
        None when the entry cannot serve this placement."""
        import jax
        from jax.experimental import serialize_executable as se

        src = [int(i) for i in entry["device_ids"]]
        dst = src if device_ids is None else [int(i) for i in device_ids]
        if dst == src:
            return se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
        if len(src) != 1 or len(dst) != 1:
            # An SPMD executable's collective schedule is baked for one
            # device set; retargeting is single-device only.
            return None
        backend = jax.devices()[0].client
        unloaded, args_info_flat, no_kwargs = _RemapUnpickler(
            io.BytesIO(entry["payload"]), backend, {src[0]: dst[0]}
        ).load()
        args_info = entry["in_tree"].unflatten(args_info_flat)
        self._metrics.counter("retarget_loads")
        return jax.stages.Compiled(
            unloaded.load(), args_info, entry["out_tree"],
            no_kwargs=no_kwargs,
        )

    # -- disk --------------------------------------------------------------
    def _read_disk(self, key: Any) -> Optional[Dict[str, Any]]:
        path = self.entry_path(key)
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as fh:
                entry = pickle.load(fh)
            if not isinstance(entry, dict) or entry.get("format") != _FORMAT:
                raise ValueError(f"bad entry format {type(entry).__name__}")
            digest = hashlib.sha256(entry["payload"]).hexdigest()
            if digest != entry["sha256"]:
                raise ValueError("payload sha256 mismatch (bit rot?)")
        except Exception as e:  # noqa: BLE001 — corrupt entry: loud miss
            self._metrics.counter("corrupt_entries")
            _log.warning(
                "corrupt compile-cache entry %s (%s: %s); deleting it and "
                "recompiling fresh", path, type(e).__name__, e,
            )
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        if entry.get("env") != self._environment():
            # A copied-in artifact from another environment: the path
            # hash should already have missed, but the embedded env is
            # the second line of defense.
            self._metrics.counter("env_mismatches")
            _log.warning(
                "compile-cache entry %s was built for a different "
                "environment (%s); ignoring it", path, entry.get("env"),
            )
            return None
        return entry

    def _write_disk(self, key: Any, entry: Dict[str, Any]) -> None:
        path = self.entry_path(key)
        if path is None:
            return
        env_dir = os.path.dirname(path)
        try:
            os.makedirs(env_dir, exist_ok=True)
            env_json = os.path.join(env_dir, "ENV.json")
            if not os.path.exists(env_json):
                import json

                with open(env_json + ".tmp", "w") as fh:
                    json.dump(entry["env"], fh, indent=2, sort_keys=True)
                os.replace(env_json + ".tmp", env_json)
            # Temp file + atomic rename (the CheckpointManager idiom):
            # a concurrent writer or a kill mid-write can never publish
            # a torn entry.
            fd, tmp = tempfile.mkstemp(dir=env_dir, prefix=".tmp-aot-")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(entry, fh, protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self._metrics.counter("stores")
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            self._metrics.counter("fallbacks")
            _log.warning(
                "could not persist compile-cache entry %s (%s: %s); the "
                "program stays in-memory only", path, type(e).__name__, e,
            )

    # -- the public entry point --------------------------------------------
    def get_or_compile(
        self,
        key: Any,
        build: Callable[[], Any],
        device_ids: Optional[Sequence[int]] = None,
    ) -> Tuple[Any, str]:
        """Load ``key``'s artifact (memory, then disk) or ``build()`` it.

        ``build`` must return a ``jax.stages.Compiled`` (i.e.
        ``jit(f).lower(*args).compile()``). ``device_ids`` is the
        placement the returned program must execute on — recorded at
        store time, retarget-matched at load time. Returns ``(program,
        outcome)`` with outcome one of ``"memory"``, ``"disk"``,
        ``"compiled"``, ``"uncached"`` (serialization unavailable or
        failed; the program came from ``build`` and was not stored).
        """
        if not serialization_supported():
            self._metrics.counter("fallbacks")
            return build(), "uncached"
        khash = _key_hash(key)
        with self._key_lock(khash):
            outcome = "memory"
            with self._lock:
                entry = self._memory.get(khash)
            if entry is None:
                entry = self._read_disk(key)
                outcome = "disk"
            if entry is not None:
                t0 = time.perf_counter()
                try:
                    program = self._load_entry(entry, device_ids)
                except Exception as e:  # noqa: BLE001 — loud fallback
                    self._metrics.counter("corrupt_entries")
                    _log.warning(
                        "loading compile-cache artifact for %s failed "
                        "(%s: %s); recompiling fresh",
                        stable_key_repr(key)[:120], type(e).__name__, e,
                    )
                    program = None
                if program is not None:
                    load_ms = (time.perf_counter() - t0) * 1000.0
                    self._metrics.counter("hits")
                    self._metrics.gauge("load_ms", load_ms)
                    self._metrics.record("load_ms", load_ms)
                    if self.directory is None:
                        # Memory-ONLY stores keep the entry — it IS the
                        # storage. Disk-backed stores re-read on the
                        # next in-process consumer instead of pinning a
                        # second copy of every executable's bytes in
                        # RAM for the process lifetime (call sites
                        # cache the LOADED program already).
                        with self._lock:
                            self._memory[khash] = entry
                    return program, outcome
            self._metrics.counter("misses")
            t0 = time.perf_counter()
            program = self._build_fresh(build)
            compile_ms = (time.perf_counter() - t0) * 1000.0
            self._metrics.gauge("compile_ms", compile_ms)
            self._metrics.record("compile_ms", compile_ms)
            entry = self._serialize(
                program, key,
                device_ids if device_ids is not None else (),
            )
            if entry is not None and not self._verify_entry(entry,
                                                            device_ids, key):
                entry = None
            if entry is None:
                return program, "uncached"
            if self.directory is None:
                with self._lock:
                    self._memory[khash] = entry
            self._write_disk(key, entry)
            return program, "compiled"

    @staticmethod
    def _build_fresh(build: Callable[[], Any]):
        """Run ``build`` with jax's own persistent compilation cache
        disabled: an executable that XLA:CPU loads from that cache
        serializes WITHOUT its jit-compiled symbols ("Symbols not
        found" at deserialize — reproduced on jax 0.4.37), so an
        artifact must always come from a fresh backend compile. This
        store replaces what the jax cache would have saved anyway."""
        import jax

        prev = jax.config.jax_compilation_cache_dir
        if prev is None:
            return build()
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            return build()
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def _verify_entry(self, entry: Dict[str, Any],
                      device_ids: Optional[Sequence[int]],
                      key: Any) -> bool:
        """Prove the just-serialized artifact actually loads BEFORE
        persisting it — a backend whose serialization is lossy (the
        symbol-stripping failure above, or any future one) degrades to
        compile-only instead of planting artifacts that poison every
        later cold start."""
        try:
            self._load_entry(entry, device_ids)
            return True
        except Exception as e:  # noqa: BLE001 — refuse to persist
            self._metrics.counter("fallbacks")
            _log.warning(
                "AOT artifact for %s failed its post-serialize load "
                "check (%s: %s); not persisting it",
                stable_key_repr(key)[:120], type(e).__name__, e,
            )
            return False


# -- the process-wide active store -------------------------------------------

_ACTIVE: list = [None]
_CONFIGURED = [False]  # explicit configure() beats the env var


def configure(store: "CompileCacheStore | str | None") -> Optional[
        CompileCacheStore]:
    """Install the process-wide store: a :class:`CompileCacheStore`, a
    directory path, or None (disable — every compile site reverts to
    plain in-memory jit). Returns the installed store."""
    if isinstance(store, str):
        store = CompileCacheStore(store)
    _ACTIVE[0] = store
    _CONFIGURED[0] = True
    return store


def active_store() -> Optional[CompileCacheStore]:
    """The process-wide store the compile sites consult: whatever
    :func:`configure` installed, else a disk store at
    ``$FLINKML_TPU_COMPILE_CACHE`` (created lazily), else None."""
    if _CONFIGURED[0]:
        return _ACTIVE[0]
    directory = os.environ.get(ENV_DIR_VAR)
    if directory:
        _ACTIVE[0] = CompileCacheStore(directory)
        _CONFIGURED[0] = True
        return _ACTIVE[0]
    return _ACTIVE[0]


def ensure_store() -> CompileCacheStore:
    """The active store, creating a process-local (memory-only) one when
    nothing is configured — what :class:`~flinkml_tpu.serving.pool
    .ReplicaPool` calls at spin-up so N replicas share one compile even
    without a cache directory."""
    store = active_store()
    if store is None:
        store = CompileCacheStore(None)
        _ACTIVE[0] = store
        _CONFIGURED[0] = True
    return store


def reset() -> None:
    """Forget the process-wide store AND re-arm the env-var lookup
    (tests)."""
    _ACTIVE[0] = None
    _CONFIGURED[0] = False
