"""Online inference engine: micro-batched, hot-swappable, admission-controlled.

The request path, end to end:

  1. ``predict()`` validates the request against the input schema fixed
     at load time (names, trailing shapes; values are cast to the schema
     dtypes, so every packed batch hits the SAME fused-cache keys) and
     offers it to the :class:`~flinkml_tpu.serving.batcher
     .AdaptiveMicroBatcher`'s bounded queue.
  2. The dispatcher thread coalesces queued requests into one
     :class:`~flinkml_tpu.table.Table` and runs the ACTIVE model's
     ``transform`` — the fused executor compiles per power-of-two row
     bucket, and the engine precompiled every bucket up to
     ``max_batch_rows`` at load, so steady state is **zero retraces**
     (guard-verifiable with
     :class:`~flinkml_tpu.analysis.guard.TransferRetraceGuard`).
  3. Output columns are materialized to host once per batch and sliced
     back per request; each response carries the model **version** that
     served it.

Hot swap: :meth:`swap_to` loads + warms the new version OFF the serving
path, then atomically replaces the active-model reference. In-flight
batches finish on the executable they snapshotted; every later batch
routes to the new version — zero downtime, zero dropped or mis-versioned
responses. Same-shape model data reuses the compiled programs outright
(constants are traced arguments), so a swap costs no steady-state
recompiles.

Graceful degradation: a full queue either rejects with the typed
:class:`~flinkml_tpu.serving.errors.ServingOverloadError` or, with
``shed_on_overload`` (default), serves the request in the CALLER's
thread through the per-stage host path — slower, but it keeps absorbing
load without growing the device queue. Requests carry deadlines;
expiry while queued or in flight raises
:class:`~flinkml_tpu.serving.errors.ServingTimeoutError`.

Coexistence with training: serving programs are single-device (the fused
executor is not SPMD today), which cannot interleave a multi-device
collective rendezvous, so by default the engine dispatches without any
cross-thread device lock and lives happily beside an in-progress
``train_*_stream`` on overlapping devices. A model whose transform IS a
multi-device collective program must be given ``config.mesh``; the
engine then wraps every batch in
``parallel.dispatch.local_execution_lock(mesh)`` and time-shares with
training the same way concurrent fits do (analyzer-verified, FML302).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.serving.batcher import AdaptiveMicroBatcher, ServingRequest
from flinkml_tpu.serving.errors import (
    EngineStoppedError,
    RegistryError,
    ServingOverloadError,
    ServingSchemaError,
    ServingTimeoutError,
)
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs (see module docstring for the policies they drive).

    ``warmup_row_counts=None`` precompiles every bucket from the minimum
    up to ``row_bucket(max_batch_rows)`` — full zero-retrace coverage.
    Pass an explicit tuple to warm fewer (new buckets still compile
    lazily on first use; the retrace guard's default policy allows
    new-bucket compiles of a known chain).
    """

    max_batch_rows: int = 1024
    max_wait_ms: float = 2.0
    max_queue_rows: int = 8192
    default_timeout_ms: Optional[float] = None
    shed_on_overload: bool = True
    warmup_row_counts: Optional[Sequence[int]] = None
    mesh: Optional[Any] = None  # DeviceMesh for SPMD-serving models
    latency_window: int = 2048  # ring size backing the p50/p99 gauges


@dataclasses.dataclass
class ServingResponse:
    """One ``predict`` result: output columns (row-sliced to the request),
    the model version that produced them, and the request's latency."""

    columns: Dict[str, np.ndarray]
    version: Optional[int]
    latency_ms: float
    shed: bool = False

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]


@dataclasses.dataclass
class _ActiveModel:
    version: Optional[int]
    model: Any


class ServingEngine:
    """See module docstring.

    ``source`` is a :class:`~flinkml_tpu.serving.registry.ModelRegistry`
    (versioned serving with hot swap) or a fixed transformer stage
    (registry-less; responses carry ``version=None``). ``example`` fixes
    the request schema: a small host Table holding exactly the columns
    clients will send (its rows are tiled for warmup, so make them
    representative). ``output_cols`` defaults to every column
    ``transform`` adds to the example.
    """

    def __init__(
        self,
        source: Union[ModelRegistry, Any],
        example: Table,
        config: Optional[ServingConfig] = None,
        output_cols: Optional[Sequence[str]] = None,
        name: str = "default",
    ):
        self.config = config or ServingConfig()
        self.name = name
        self._registry = source if isinstance(source, ModelRegistry) else None
        self._fixed_model = None if self._registry is not None else source
        self._schema = {
            n: (np.asarray(example.column(n)).dtype,
                np.asarray(example.column(n)).shape[1:])
            for n in example.column_names
        }
        self._example = Table({
            n: np.asarray(example.column(n)) for n in example.column_names
        })
        self._output_cols: Optional[Tuple[str, ...]] = (
            tuple(output_cols) if output_cols is not None else None
        )
        self._metrics = metrics.group(f"serving.{name}")
        self._batcher = AdaptiveMicroBatcher(
            max_batch_rows=self.config.max_batch_rows,
            max_wait_s=self.config.max_wait_ms / 1000.0,
            max_queue_rows=self.config.max_queue_rows,
        )
        self._active: Optional[_ActiveModel] = None
        self._swap_lock = threading.Lock()
        # Serializes pointer-FOLLOWING swaps (listener delivery + the
        # follow_registry catch-up): each re-reads CURRENT under this
        # lock, so racing swap threads converge on the newest pointer
        # instead of flipping the active model out of order.
        self._follow_swap_lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._latencies: collections.deque = collections.deque(
            maxlen=self.config.latency_window
        )
        # Appended by the dispatcher AND by shedding caller threads;
        # iterating a deque during a concurrent append raises, so both
        # sides go through _record_latency/_update_latency_gauges.
        self._lat_lock = threading.Lock()
        self._following = False       # listener currently registered
        self._follow_requested = False  # survives stop(): restart re-follows

    # -- lifecycle ---------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def active_version(self) -> Optional[int]:
        active = self._active
        return active.version if active else None

    def start(self) -> "ServingEngine":
        """Load the model (registry: current version), precompile every
        warmup bucket, and start the dispatcher thread. Returns self."""
        if self.running:
            return self
        if self._batcher._stopped:  # restart after stop(): fresh queue
            self._batcher = AdaptiveMicroBatcher(
                max_batch_rows=self.config.max_batch_rows,
                max_wait_s=self.config.max_wait_ms / 1000.0,
                max_queue_rows=self.config.max_queue_rows,
            )
        if self._registry is not None:
            version, model = self._registry.get()
        else:
            version, model = None, self._fixed_model
        self._install(version, model)
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._dispatch_loop,
            name=f"serving-{self.name}",
            daemon=True,
        )
        self._thread.start()
        if self._follow_requested:  # re-follow across a stop()/start() cycle
            self.follow_registry()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop accepting requests; with ``drain`` (default) the
        dispatcher finishes everything already queued, otherwise queued
        requests fail with :class:`EngineStoppedError`."""
        self._batcher.stop()
        if not drain:
            for req in self._batcher.drain_pending():
                req.fail(EngineStoppedError("serving engine stopped"))
        self._stop_event.set()
        # Unfollow BEFORE the join (safe regardless of its outcome): a
        # stopped engine must not keep paying load+warmup in publishing
        # threads on every registry event.
        if self._following and self._registry is not None:
            self._registry.remove_listener(self._on_registry_change)
            self._following = False
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # join timed out mid-batch: keep the reference so running
                # stays True and start() cannot spawn a second dispatcher
                # over the same batcher while the orphan drains.
                return
            self._thread = None

    # -- hot swap ----------------------------------------------------------
    def swap_to(self, version: Optional[int] = None) -> int:
        """Load ``version`` (default: the registry's current) and swap it
        in with zero downtime: the load + per-bucket warmup run in the
        calling thread while the dispatcher keeps serving the old model;
        only the final reference flip is atomic. Returns the version."""
        if self._registry is None:
            raise RegistryError(
                "swap_to requires a ModelRegistry-backed engine"
            )
        v, model = self._registry.get(version)
        self._install(v, model)
        return v

    def follow_registry(self) -> "ServingEngine":
        """Auto-swap on every registry publish/rollback (the swap —
        including warmup — runs in the publishing thread)."""
        if self._registry is None:
            raise RegistryError(
                "follow_registry requires a ModelRegistry-backed engine"
            )
        self._follow_requested = True
        if not self._following:
            self._registry.add_listener(self._on_registry_change)
            self._following = True
        # Catch-up swap: a publish that landed between our load and the
        # listener registration would otherwise never be delivered.
        self._swap_to_current()
        return self

    def _on_registry_change(self, version: int) -> None:
        self._swap_to_current()

    def _swap_to_current(self) -> None:
        """Install whatever CURRENT points at right now (no-op when it is
        already active). Re-reading the pointer under the serialization
        lock makes concurrent deliveries converge on the newest version —
        a slow catch-up swap cannot overwrite a newer listener swap."""
        with self._follow_swap_lock:
            current = self._registry.current_version()
            if current is None:
                return
            active = self._active
            if active is not None and active.version == current:
                return
            v, model = self._registry.get(current)
            self._install(v, model)

    def _install(self, version: Optional[int], model: Any) -> None:
        # Warmup dispatches real transforms: SPMD engines (config.mesh)
        # must hold the mesh lock here too, or the load/swap path would
        # interleave collective rendezvous with a concurrent trainer —
        # the same hazard _serve_batch guards against. Single-device
        # engines get a nullcontext.
        with self._dispatch_guard():
            buckets = self._warmup(model)
        with self._swap_lock:
            first = self._active is None
            self._active = _ActiveModel(version, model)
        if not first:
            self._metrics.counter("swaps")
        if version is not None:
            self._metrics.gauge("active_version", version)
        self._metrics.gauge("warmed_buckets", float(len(buckets)))

    def _warmup(self, model: Any) -> List[int]:
        cfg = self.config
        row_counts = (
            cfg.warmup_row_counts
            if cfg.warmup_row_counts is not None
            else _all_buckets_up_to(cfg.max_batch_rows)
        )
        buckets, read = pipeline_fusion.warmup_transform(
            model, self._example, row_counts,
            output_cols=self._output_cols or (),
        )
        if self._output_cols is None:
            if not read:  # warmup disabled (empty row_counts): discover
                (out,) = model.transform(self._example)
                read = tuple(
                    c for c in out.column_names
                    if c not in self._example.column_names
                )
            if not read:
                # A model that only overwrites its input columns in place
                # defeats added-column discovery — silent empty responses
                # would be far worse than failing the load.
                raise ServingSchemaError(
                    "could not infer output columns: transform adds no new "
                    "columns to the example (in-place overwrite?); pass "
                    "output_cols= explicitly"
                )
            self._output_cols = read  # discovered during warmup, for free
        return buckets

    # -- request path ------------------------------------------------------
    def predict(
        self,
        features: Union[Table, Mapping[str, Any]],
        timeout_ms: Optional[float] = None,
    ) -> ServingResponse:
        """Synchronous prediction: enqueue, micro-batch, return the
        request's slice of the batch output. Thread-safe; call it from as
        many client threads as you like."""
        self._check_running()
        columns, rows = self._normalize(features)
        t0 = time.monotonic()
        timeout = (
            timeout_ms if timeout_ms is not None
            else self.config.default_timeout_ms
        )
        deadline = t0 + timeout / 1000.0 if timeout is not None else None
        req = ServingRequest(
            columns=columns, rows=rows, enqueued_at=t0, deadline=deadline
        )
        self._metrics.counter("requests")
        self._metrics.counter("rows", float(rows))
        if not self._batcher.offer(req):
            return self._overloaded(req, t0)
        self._metrics.gauge("queue_depth", self._batcher.queue_depth)
        remaining = None if deadline is None else max(
            0.0, deadline - time.monotonic()
        )
        # Grace on top of the deadline: the dispatcher expires queued
        # requests itself; in-flight batches get a moment to finish.
        if not req.done.wait(None if remaining is None else remaining + 0.25):
            if req.claim_timeout_count():
                self._metrics.counter("timeouts")
            raise ServingTimeoutError(
                f"request did not complete within {timeout}ms"
            )
        if req.error is not None:
            raise req.error
        latency_ms = (time.monotonic() - t0) * 1000.0
        return ServingResponse(
            columns=req.result, version=req.version,
            latency_ms=latency_ms, shed=req.shed,
        )

    def _overloaded(self, req: ServingRequest, t0: float) -> ServingResponse:
        """Queue-full policy: shed to the per-stage host path in the
        caller's thread, or reject with the typed overload error. The
        deadline contract survives shedding: an already-expired request
        times out instead of blocking the caller on the slower path."""
        if not self.config.shed_on_overload:
            self._metrics.counter("rejected")
            raise ServingOverloadError(
                f"serving queue full ({self._batcher.max_queue_rows} rows); "
                "retry with backoff"
            )
        if req.deadline is not None and req.deadline <= time.monotonic():
            if req.claim_timeout_count():
                self._metrics.counter("timeouts")
            raise ServingTimeoutError(
                "request deadline expired at admission (queue saturated)"
            )
        self._metrics.counter("shed_requests")
        active = self._active
        # Same locking discipline as _serve_batch/_install: an SPMD
        # engine's per-stage transform still dispatches multi-device
        # programs, so shedding must not bypass the mesh lock (and the
        # dispatch stays visible to the FML302 trace audit).
        with self._dispatch_guard():
            from flinkml_tpu.parallel import dispatch as _dispatch

            if _dispatch.has_dispatch_observers():
                _dispatch.record_collective_dispatch(
                    "serving.shed", self._device_ids()
                )
            table = _transform_per_stage(active.model, Table(req.columns))
            result = {
                c: np.asarray(table.column(c)) for c in self._output_cols
            }
        latency_ms = (time.monotonic() - t0) * 1000.0
        self._record_latency(latency_ms)
        return ServingResponse(
            columns=result, version=active.version,
            latency_ms=latency_ms, shed=True,
        )

    def _normalize(
        self, features: Union[Table, Mapping[str, Any]]
    ) -> Tuple[Dict[str, np.ndarray], int]:
        if isinstance(features, Table):
            features = {n: features.column(n) for n in features.column_names}
        if set(features.keys()) != set(self._schema.keys()):
            raise ServingSchemaError(
                f"request columns {sorted(features.keys())} != schema "
                f"columns {sorted(self._schema.keys())}"
            )
        out: Dict[str, np.ndarray] = {}
        rows: Optional[int] = None
        for name, (dtype, trailing) in self._schema.items():
            a = np.asarray(features[name], dtype=dtype)
            if a.ndim == len(trailing):  # single row, leading axis omitted
                a = a[None]
            if a.shape[1:] != trailing:
                raise ServingSchemaError(
                    f"column {name!r} has trailing shape {a.shape[1:]}, "
                    f"schema expects {trailing}"
                )
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ServingSchemaError(
                    f"column {name!r} has {a.shape[0]} rows, others have "
                    f"{rows}"
                )
            out[name] = a
        if not rows:
            raise ServingSchemaError("empty request (zero rows)")
        if rows > self.config.max_batch_rows:
            raise ServingSchemaError(
                f"request has {rows} rows > max_batch_rows "
                f"{self.config.max_batch_rows}; split it client-side"
            )
        return out, rows

    # -- dispatcher --------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            batch, expired = self._batcher.next_batch(poll_s=0.02)
            for req in expired:
                if req.claim_timeout_count():
                    self._metrics.counter("timeouts")
                req.fail(ServingTimeoutError(
                    "request expired while queued (deadline passed before "
                    "dispatch)"
                ))
            if batch:
                self._serve_batch(batch)
            elif self._stop_event.is_set() and self._batcher.queue_depth == 0:
                return
            self._metrics.gauge("queue_depth", self._batcher.queue_depth)

    def _serve_batch(self, batch: List[ServingRequest]) -> None:
        active = self._active  # snapshot: in-flight work stays on it
        rows = sum(r.rows for r in batch)
        packed = {
            name: (
                np.concatenate([r.columns[name] for r in batch])
                if len(batch) > 1 else batch[0].columns[name]
            )
            for name in self._schema
        }
        try:
            table = Table(packed)
            with self._dispatch_guard():
                from flinkml_tpu.parallel import dispatch as _dispatch

                if _dispatch.has_dispatch_observers():
                    # The event carries the lock tokens this thread holds,
                    # so analysis.collectives.check_dispatch_trace can
                    # audit serving+training runs (FML302).
                    _dispatch.record_collective_dispatch(
                        "serving.batch", self._device_ids()
                    )
                (out,) = active.model.transform(table)
                host = {
                    c: np.asarray(out.column(c)) for c in self._output_cols
                }
        except BaseException as e:  # noqa: BLE001 — fail the batch, not the loop
            self._metrics.counter("errors")
            for req in batch:
                req.fail(e)
            return
        bucket = pipeline_fusion.row_bucket(rows)
        self._metrics.counter("batches")
        self._metrics.counter("batch_rows", float(rows))
        self._metrics.counter("batch_padded_rows", float(bucket))
        self._metrics.gauge("last_batch_occupancy", rows / bucket)
        now = time.monotonic()
        offset = 0
        completions = []
        for req in batch:
            # Copies, not views: responses to different clients must not
            # alias one batch buffer (a client post-processing its arrays
            # in place would corrupt its batchmates' results).
            sliced = {
                c: host[c][offset:offset + req.rows].copy() for c in host
            }
            offset += req.rows
            completions.append((req, sliced))
        with self._lat_lock:  # one acquisition for the whole batch
            self._latencies.extend(
                (now - req.enqueued_at) * 1000.0 for req in batch
            )
        # Gauges first, completions second: a client reading stats right
        # after its predict() returns sees its own request reflected.
        self._update_latency_gauges()
        for req, sliced in completions:
            req.complete(sliced, active.version)

    def _dispatch_guard(self):
        """Multi-device serving programs time-share devices with training
        via the mesh lock; single-device programs (the fused executor's
        output) need no cross-thread lock — see module docstring."""
        if self.config.mesh is None:
            return contextlib.nullcontext()
        from flinkml_tpu.parallel.dispatch import local_execution_lock

        return local_execution_lock(self.config.mesh)

    def _device_ids(self) -> Tuple[int, ...]:
        if self.config.mesh is not None:
            mesh = getattr(self.config.mesh, "mesh", self.config.mesh)
            return tuple(d.id for d in mesh.devices.flatten())
        import jax

        return (jax.devices()[0].id,)

    def _record_latency(self, latency_ms: float) -> None:
        with self._lat_lock:
            self._latencies.append(latency_ms)
        self._update_latency_gauges()

    def _update_latency_gauges(self) -> None:
        with self._lat_lock:
            if not self._latencies:
                return
            arr = np.asarray(self._latencies)
        p50, p99 = np.percentile(arr, [50, 99])  # one sort for both
        self._metrics.gauge("p50_ms", float(p50))
        self._metrics.gauge("p99_ms", float(p99))

    def _check_running(self) -> None:
        if not self.running:
            raise EngineStoppedError(
                "serving engine is not running; call start()"
            )

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Point-in-time operational snapshot (the stats-endpoint dump)."""
        snap = self._metrics.snapshot()
        return {
            "name": self.name,
            "running": self.running,
            "active_version": self.active_version,
            "queue_depth": self._batcher.queue_depth,
            "queued_rows": self._batcher.queued_rows,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }

    def stats_text(self) -> str:
        """Prometheus-style exposition of the whole process registry
        (:meth:`flinkml_tpu.utils.metrics.MetricsRegistry.render_text`)."""
        from flinkml_tpu.utils.metrics import default_registry

        return default_registry().render_text()


def _all_buckets_up_to(max_rows: int) -> List[int]:
    buckets = []
    b = pipeline_fusion.MIN_ROW_BUCKET
    top = pipeline_fusion.row_bucket(max_rows)
    while b <= top:
        buckets.append(b)
        b *= 2
    return buckets


def _transform_per_stage(model: Any, table: Table) -> Table:
    """The host (unfused) path: chain each stage's own ``transform``.
    Identical semantics to ``PipelineModel.transform`` with fusion
    disabled, without touching the process-wide fusion switch (other
    threads may be mid-fused-dispatch)."""
    stages = getattr(model, "stages", None)
    if stages is None:
        (out,) = model.transform(table)
        return out
    for stage in stages:
        (table,) = stage.transform(table)
    return table
