"""Online inference engine: micro-batched, hot-swappable, admission-controlled.

The request path, end to end:

  1. ``predict()`` validates the request against the input schema fixed
     at load time (names, trailing shapes; values are cast to the schema
     dtypes, so every packed batch hits the SAME fused-cache keys) and
     offers it to the :class:`~flinkml_tpu.serving.batcher
     .AdaptiveMicroBatcher`'s bounded queue.
  2. The dispatcher thread coalesces queued requests into one
     :class:`~flinkml_tpu.table.Table` and runs the ACTIVE model's
     ``transform`` — the fused executor compiles per power-of-two row
     bucket, and the engine precompiled every bucket up to
     ``max_batch_rows`` at load, so steady state is **zero retraces**
     (guard-verifiable with
     :class:`~flinkml_tpu.analysis.guard.TransferRetraceGuard`).
  3. Output columns are materialized to host once per batch and sliced
     back per request; each response carries the model **version** that
     served it.

Hot swap: :meth:`swap_to` loads + warms the new version OFF the serving
path, then atomically replaces the active-model reference. In-flight
batches finish on the executable they snapshotted; every later batch
routes to the new version — zero downtime, zero dropped or mis-versioned
responses. Same-shape model data reuses the compiled programs outright
(constants are traced arguments), so a swap costs no steady-state
recompiles.

Graceful degradation: a full queue either rejects with the typed
:class:`~flinkml_tpu.serving.errors.ServingOverloadError` or, with
``shed_on_overload`` (default), serves the request in the CALLER's
thread through the per-stage host path — slower, but it keeps absorbing
load without growing the device queue. Requests carry deadlines;
expiry while queued or in flight raises
:class:`~flinkml_tpu.serving.errors.ServingTimeoutError`.

Coexistence with training: serving programs are single-device (the fused
executor is not SPMD today), which cannot interleave a multi-device
collective rendezvous, so by default the engine dispatches without any
cross-thread device lock and lives happily beside an in-progress
``train_*_stream`` on overlapping devices. A model whose transform IS a
multi-device collective program must be given ``config.mesh``; the
engine then wraps every batch in
``parallel.dispatch.local_execution_lock(mesh)`` and time-shares with
training the same way concurrent fits do (analyzer-verified, FML302).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

import flinkml_tpu.faults as faults
from flinkml_tpu import pipeline_fusion
from flinkml_tpu.serving.batcher import (
    AdaptiveMicroBatcher,
    BatchSegment,
    ContinuousBatcher,
    ServingRequest,
)
from flinkml_tpu.serving.errors import (
    EngineStoppedError,
    RegistryError,
    ServingMemoryError,
    ServingOverloadError,
    ServingSchemaError,
    ServingTimeoutError,
)
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics


def _tuned_int(knob: str, fallback: int) -> int:
    """An autotuned integer knob, degraded to ``fallback`` when the
    table value is non-numeric or non-positive (a config-table typo must
    not take serving down)."""
    from flinkml_tpu.autotune import tuned_default

    try:
        value = int(tuned_default(knob, fallback))
    except (TypeError, ValueError):
        return fallback
    return value if value >= 1 else fallback


def _tuned_float(knob: str, fallback: float) -> float:
    from flinkml_tpu.autotune import tuned_default

    try:
        value = float(tuned_default(knob, fallback))
    except (TypeError, ValueError):
        return fallback
    return value if value > 0 else fallback


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs (see module docstring for the policies they drive).

    ``warmup_row_counts=None`` precompiles every bucket from the minimum
    up to ``row_bucket(max_batch_rows)`` — full zero-retrace coverage.
    Pass an explicit tuple to warm fewer (new buckets still compile
    lazily on first use; the retrace guard's default policy allows
    new-bucket compiles of a known chain).

    ``batching`` selects the queue policy: ``"continuous"`` (default —
    requests split at bucket boundaries, Orca-style; see
    :class:`~flinkml_tpu.serving.batcher.ContinuousBatcher`) or
    ``"fifo"`` (PR 3's whole-request packing, kept for A/B comparison —
    the ``serving_scaleout`` bench stage measures both).

    ``device`` pins every dispatch (warmup included) to one
    ``jax.Device`` via ``jax.default_device`` — how a
    :class:`~flinkml_tpu.serving.pool.ReplicaPool` places one replica
    per device. ``metrics_name``/``metrics_labels`` let several engines
    share one metric GROUP distinguished by labels (per-replica gauges
    aggregate instead of colliding); ``dispatch_tag`` overrides the
    program name recorded for dispatch-trace observers (the pool tags
    replicas ``serving.pool/<pool>/<replica>`` so the analyzer's FML303
    check can see pool slices).

    ``max_batch_rows`` (the power-of-two dispatch bucket cap) and
    ``max_wait_ms`` (the batching window) default to None = the
    MEASURED value for this mesh from the autotune tuning table
    (knobs ``serving_max_batch_rows`` / ``serving_window_ms``; see
    ``docs/development/compile_cache.md``), falling back to the
    historical 1024 rows / 2 ms. An explicit value always wins.
    """

    max_batch_rows: Optional[int] = None
    max_wait_ms: Optional[float] = None
    max_queue_rows: int = 8192
    default_timeout_ms: Optional[float] = None
    shed_on_overload: bool = True
    warmup_row_counts: Optional[Sequence[int]] = None
    mesh: Optional[Any] = None  # DeviceMesh for SPMD-serving models
    latency_window: int = 2048  # ring size backing the p50/p99 gauges
    batching: str = "continuous"  # or "fifo"
    device: Optional[Any] = None  # jax.Device to pin all dispatches to
    metrics_name: Optional[str] = None  # metric group name (default: name)
    metrics_labels: Optional[Dict[str, str]] = None
    dispatch_tag: Optional[str] = None  # trace program prefix override
    # Refuse to install a model whose learned arrays hold non-finite
    # values (NonFiniteModelError at load/swap time — the serving half
    # of the self-healing contract; a follower's refused swap keeps the
    # old model serving).
    refuse_nonfinite: bool = True
    # Mixed-precision contract for every fused inference program this
    # engine compiles: a PrecisionPolicy, preset name ("mixed_inference"
    # is the serving preset), or policy JSON dict. Each program is
    # FML6xx-validated against the policy BEFORE compile — at warmup, so
    # a policy-violating model is refused at LOAD time
    # (PrecisionValidationError) and a follower's refused swap keeps the
    # previous model serving, exactly like refuse_nonfinite. The
    # shed-to-host degradation path runs per-stage at full width (it
    # exists to avoid the fused executor entirely); see
    # docs/development/precision.md.
    precision: Optional[Any] = None
    # Per-device HBM budget for the load-time memory gate: a model whose
    # estimated footprint (learned arrays at this engine's precision
    # tier + batch buffers at the largest dispatch bucket; see
    # analysis.memory.estimate_serving_bytes) exceeds the budget is
    # refused with ServingMemoryError BEFORE the active-model flip —
    # the refuse_nonfinite idiom applied to capacity. None disables.
    hbm_budget_bytes: Optional[int] = None


@dataclasses.dataclass
class ServingResponse:
    """One ``predict`` result: output columns (row-sliced to the request),
    the model version that produced them, and the request's latency."""

    columns: Dict[str, np.ndarray]
    version: Optional[int]
    latency_ms: float
    shed: bool = False

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]


@dataclasses.dataclass
class _ActiveModel:
    version: Optional[int]
    model: Any


class ServingEngine:
    """See module docstring.

    ``source`` is a :class:`~flinkml_tpu.serving.registry.ModelRegistry`
    (versioned serving with hot swap) or a fixed transformer stage
    (registry-less; responses carry ``version=None``). ``example`` fixes
    the request schema: a small host Table holding exactly the columns
    clients will send (its rows are tiled for warmup, so make them
    representative). ``output_cols`` defaults to every column
    ``transform`` adds to the example.
    """

    def __init__(
        self,
        source: Union[ModelRegistry, Any],
        example: Table,
        config: Optional[ServingConfig] = None,
        output_cols: Optional[Sequence[str]] = None,
        name: str = "default",
    ):
        cfg = config or ServingConfig()
        # Resolve the autotuned knobs ONCE, at construction: everything
        # downstream (batcher bounds, warmup bucket coverage, request
        # validation) reads concrete values. A bad TABLE value degrades
        # to the static default (the tuned_default contract: a stale or
        # hand-edited table must never take serving down) — an explicit
        # bad value still fails loudly in the batcher's own validation.
        self.config = dataclasses.replace(
            cfg,
            max_batch_rows=(
                int(cfg.max_batch_rows)
                if cfg.max_batch_rows is not None
                else _tuned_int("serving_max_batch_rows", 1024)
            ),
            max_wait_ms=(
                float(cfg.max_wait_ms)
                if cfg.max_wait_ms is not None
                else _tuned_float("serving_window_ms", 2.0)
            ),
        )
        self.name = name
        self._registry = source if isinstance(source, ModelRegistry) else None
        self._fixed_model = None if self._registry is not None else source
        self._schema = {
            n: (np.asarray(example.column(n)).dtype,
                np.asarray(example.column(n)).shape[1:])
            for n in example.column_names
        }
        self._example = Table({
            n: np.asarray(example.column(n)) for n in example.column_names
        })
        self._output_cols: Optional[Tuple[str, ...]] = (
            tuple(output_cols) if output_cols is not None else None
        )
        from flinkml_tpu.precision import resolve_policy

        # Resolved once (a bad preset name fails construction, not the
        # first swap); every fused dispatch below runs under this scope.
        self._policy = resolve_policy(self.config.precision)
        self._metrics = metrics.group(
            f"serving.{self.config.metrics_name or name}",
            labels=self.config.metrics_labels,
        )
        if self.config.batching not in ("continuous", "fifo"):
            raise ValueError(
                f"batching must be 'continuous' or 'fifo', got "
                f"{self.config.batching!r}"
            )
        self._batcher = self._make_batcher()
        self._active: Optional[_ActiveModel] = None
        self._swap_lock = threading.Lock()
        # Serializes pointer-FOLLOWING swaps (listener delivery + the
        # follow_registry catch-up): each re-reads CURRENT under this
        # lock, so racing swap threads converge on the newest pointer
        # instead of flipping the active model out of order.
        self._follow_swap_lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        # Fed by the dispatcher AND by shedding caller threads — the
        # shared window serializes them and publishes p50/p99 gauges.
        from flinkml_tpu.utils.metrics import LatencyWindow

        self._latency_window = LatencyWindow(
            self._metrics, self.config.latency_window
        )
        self._following = False       # listener currently registered
        self._follow_requested = False  # survives stop(): restart re-follows

    def _make_batcher(self) -> AdaptiveMicroBatcher:
        cls = (
            ContinuousBatcher if self.config.batching == "continuous"
            else AdaptiveMicroBatcher
        )
        return cls(
            max_batch_rows=self.config.max_batch_rows,
            max_wait_s=self.config.max_wait_ms / 1000.0,
            max_queue_rows=self.config.max_queue_rows,
        )

    # -- lifecycle ---------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def active_version(self) -> Optional[int]:
        active = self._active
        return active.version if active else None

    @property
    def queued_rows(self) -> int:
        """Rows currently queued in the batcher — the public backlog
        signal (the pool autoscaler and the multi-model scale target
        both consume it; don't reach for ``_batcher``)."""
        return self._batcher.queued_rows

    @property
    def observed_p99_ms(self) -> Optional[float]:
        """The latest p99 latency gauge (None before any completion) —
        the public latency signal for autoscaling."""
        p99 = self._metrics.snapshot()["gauges"].get("p99_ms")
        return float(p99) if isinstance(p99, (int, float)) else None

    def start(self) -> "ServingEngine":
        """Load the model (registry: current version), precompile every
        warmup bucket, and start the dispatcher thread. Returns self."""
        if self.running:
            return self
        if self._batcher._stopped:  # restart after stop(): fresh queue
            self._batcher = self._make_batcher()
        if self._registry is not None:
            version, model = self._registry.get()
        else:
            version, model = None, self._fixed_model
        self._install(version, model)
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._dispatch_loop,
            name=f"serving-{self.name}",
            daemon=True,
        )
        self._thread.start()
        if self._follow_requested:  # re-follow across a stop()/start() cycle
            self.follow_registry()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop accepting requests; with ``drain`` (default) the
        dispatcher finishes everything already queued, otherwise queued
        requests fail with :class:`EngineStoppedError`."""
        self._batcher.stop()
        if not drain:
            for req in self._batcher.drain_pending():
                req.fail(EngineStoppedError("serving engine stopped"))
        self._stop_event.set()
        # Unfollow BEFORE the join (safe regardless of its outcome): a
        # stopped engine must not keep paying load+warmup in publishing
        # threads on every registry event.
        if self._following and self._registry is not None:
            self._registry.remove_listener(self._on_registry_change)
            self._following = False
        # Local capture: stop() may run concurrently (the pool's retire
        # thread and pool.stop() both stop a dead replica) and the loser
        # must not trip over the winner clearing self._thread.
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                # join timed out mid-batch: keep the reference so running
                # stays True and start() cannot spawn a second dispatcher
                # over the same batcher while the orphan drains.
                return
            self._thread = None

    # -- hot swap ----------------------------------------------------------
    def swap_to(self, version: Optional[int] = None) -> int:
        """Load ``version`` (default: the registry's current) and swap it
        in with zero downtime: the load + per-bucket warmup run in the
        calling thread while the dispatcher keeps serving the old model;
        only the final reference flip is atomic. Returns the version."""
        if self._registry is None:
            raise RegistryError(
                "swap_to requires a ModelRegistry-backed engine"
            )
        target = (int(version) if version is not None
                  else self._registry.current_version())
        if target is not None and self._try_delta_swap(target):
            return target
        v, model = self._registry.get(version)
        self._install(v, model)
        return v

    def follow_registry(self) -> "ServingEngine":
        """Auto-swap on every registry publish/rollback (the swap —
        including warmup — runs in the publishing thread)."""
        if self._registry is None:
            raise RegistryError(
                "follow_registry requires a ModelRegistry-backed engine"
            )
        self._follow_requested = True
        if not self._following:
            self._registry.add_listener(self._on_registry_change)
            self._following = True
        # Catch-up swap: a publish that landed between our load and the
        # listener registration would otherwise never be delivered.
        self._swap_to_current()
        return self

    def _on_registry_change(self, version: int) -> None:
        self._swap_to_current()

    def _swap_to_current(self) -> None:
        """Install whatever CURRENT points at right now (no-op when it is
        already active). Re-reading the pointer under the serialization
        lock makes concurrent deliveries converge on the newest version —
        a slow catch-up swap cannot overwrite a newer listener swap."""
        with self._follow_swap_lock:
            current = self._registry.current_version()
            if current is None:
                return
            active = self._active
            if active is not None and active.version == current:
                return
            if self._try_delta_swap(current):
                return
            v, model = self._registry.get(current)
            self._install(v, model)

    def _try_delta_swap(self, target: int) -> bool:
        """The incremental-publish fast path: when the registry holds an
        unbroken delta chain from the ACTIVE version to ``target`` and
        the active model is delta-capable, patch a clone in place —
        no full model load, no warmup (row patches keep every shape, so
        the compiled dispatch programs are reused as-is) — and flip it
        atomically. The old model object is untouched, so an in-flight
        batch that snapshotted it still serves exactly one version (the
        PR 8 contract). Returns False (caller falls back to a verified
        full load) on any miss: registry-less engine, no active model,
        no chain, fingerprint mismatch, or a lost race with a concurrent
        full install."""
        active = self._active
        if (self._registry is None or active is None
                or active.version is None
                or not hasattr(active.model, "apply_delta")
                or not hasattr(active.model, "delta_state")):
            return False
        chain = self._registry.delta_chain(active.version, target)
        if not chain:
            return False
        from flinkml_tpu.io.read_write import content_fingerprint

        try:
            # One cheap link check anchors the chain to the live model:
            # chain-internal links were verified at publish/get time, so
            # version linkage plus this base fingerprint makes the
            # patched state bitwise what a full load would produce.
            if chain[0].base_fingerprint != content_fingerprint(
                    active.model.delta_state()):
                return False
            model = active.model
            for d in chain:
                model = model.apply_delta(d)
            if self.config.refuse_nonfinite:
                from flinkml_tpu.recovery.sentinel import check_stage_finite

                check_stage_finite(
                    model,
                    where=(f"serve (engine {self.name!r}, delta swap to "
                           f"version {target})"),
                )
        except Exception:
            # Any resolution/patch failure falls back to the fully
            # verified load path, which raises the typed error.
            return False
        with self._swap_lock:
            if self._active is not active:
                return False  # a concurrent install won; let it stand
            self._active = _ActiveModel(target, model)
        self._metrics.counter("swaps")
        self._metrics.counter("delta_swaps")
        self._metrics.gauge("active_version", target)
        return True

    def _install(self, version: Optional[int], model: Any) -> None:
        if self.config.mesh is not None and hasattr(model, "for_mesh"):
            # Mesh-bindable models (flinkml_tpu.embeddings.serving): the
            # shared source model carries host state only; each SPMD
            # engine binds a clone PLACED on its own mesh slice here, so
            # a ReplicaPool over slice_meshes loads one sharded table
            # per replica instead of racing per-replica placements on a
            # shared object.
            model = model.for_mesh(self.config.mesh)
        if self.config.refuse_nonfinite:
            # Refuse BEFORE warmup/flip: a follower's failed swap keeps
            # the previous (finite) model serving — the registry's own
            # publish check makes this a second line of defense, not the
            # first.
            from flinkml_tpu.recovery.sentinel import check_stage_finite

            check_stage_finite(
                model,
                where=f"serve (engine {self.name!r}, version {version})",
            )
        if self.config.hbm_budget_bytes is not None:
            # Budget gate, also BEFORE warmup/flip: estimate the model's
            # per-device footprint at this engine's precision tier and
            # refuse a model that cannot fit — a follower's refused swap
            # keeps the old (fitting) model serving instead of OOMing
            # the replica mid-swap.
            from flinkml_tpu.analysis.memory import estimate_serving_bytes
            from flinkml_tpu.sharding.plan import human_bytes

            budget = int(self.config.hbm_budget_bytes)
            est = estimate_serving_bytes(
                model, self._schema, self.config.max_batch_rows,
                policy=self._policy,
            )
            if est > budget:
                raise ServingMemoryError(
                    f"engine {self.name!r} refuses model version "
                    f"{version}: estimated per-device footprint "
                    f"{human_bytes(est)} exceeds hbm_budget_bytes="
                    f"{human_bytes(budget)} (learned arrays at the "
                    f"{self._policy.name if self._policy else 'full'} "
                    f"tier + 3 batch buffers at max_batch_rows="
                    f"{self.config.max_batch_rows}); the previous model "
                    "keeps serving"
                )
        # Warmup dispatches real transforms: SPMD engines (config.mesh)
        # must hold the mesh lock here too, or the load/swap path would
        # interleave collective rendezvous with a concurrent trainer —
        # the same hazard _serve_batch guards against. Single-device
        # engines get a nullcontext. Warmup runs under the engine's
        # precision scope, so the FML6xx pre-compile gate fires HERE: a
        # policy-violating model fails the install (the old model keeps
        # serving) instead of failing live traffic.
        with self._dispatch_guard(), \
                pipeline_fusion.precision_scope(self._policy):
            buckets = self._warmup(model)
        with self._swap_lock:
            first = self._active is None
            self._active = _ActiveModel(version, model)
        # Full (load+warmup) installs are counted so the freshness loop
        # can assert the hot path never re-ships the whole model.
        self._metrics.counter("full_loads")
        if not first:
            self._metrics.counter("swaps")
        if version is not None:
            self._metrics.gauge("active_version", version)
        self._metrics.gauge("warmed_buckets", float(len(buckets)))

    def _warmup(self, model: Any) -> List[int]:
        cfg = self.config
        row_counts = (
            cfg.warmup_row_counts
            if cfg.warmup_row_counts is not None
            else _all_buckets_up_to(cfg.max_batch_rows)
        )
        buckets, read = pipeline_fusion.warmup_transform(
            model, self._example, row_counts,
            output_cols=self._output_cols or (),
        )
        if self._output_cols is None:
            if not read:  # warmup disabled (empty row_counts): discover
                (out,) = model.transform(self._example)
                read = tuple(
                    c for c in out.column_names
                    if c not in self._example.column_names
                )
            if not read:
                # A model that only overwrites its input columns in place
                # defeats added-column discovery — silent empty responses
                # would be far worse than failing the load.
                raise ServingSchemaError(
                    "could not infer output columns: transform adds no new "
                    "columns to the example (in-place overwrite?); pass "
                    "output_cols= explicitly"
                )
            self._output_cols = read  # discovered during warmup, for free
        return buckets

    # -- request path ------------------------------------------------------
    def predict(
        self,
        features: Union[Table, Mapping[str, Any]],
        timeout_ms: Optional[float] = None,
    ) -> ServingResponse:
        """Synchronous prediction: enqueue, micro-batch, return the
        request's slice of the batch output. Thread-safe; call it from as
        many client threads as you like."""
        self._check_running()
        columns, rows = self._normalize(features)
        t0 = time.monotonic()
        timeout = (
            timeout_ms if timeout_ms is not None
            else self.config.default_timeout_ms
        )
        deadline = t0 + timeout / 1000.0 if timeout is not None else None
        req = ServingRequest(
            columns=columns, rows=rows, enqueued_at=t0, deadline=deadline
        )
        self._metrics.counter("requests")
        self._metrics.counter("rows", float(rows))
        if not self._batcher.offer(req):
            return self._overloaded(req, t0)
        self._metrics.gauge("queue_depth", self._batcher.queue_depth)
        remaining = None if deadline is None else max(
            0.0, deadline - time.monotonic()
        )
        # Grace on top of the deadline: the dispatcher expires queued
        # requests itself; in-flight batches get a moment to finish.
        if not req.done.wait(None if remaining is None else remaining + 0.25):
            if req.claim_timeout_count():
                self._metrics.counter("timeouts")
            raise ServingTimeoutError(
                f"request did not complete within {timeout}ms"
            )
        if req.error is not None:
            raise req.error
        latency_ms = (time.monotonic() - t0) * 1000.0
        return ServingResponse(
            columns=req.result, version=req.version,
            latency_ms=latency_ms, shed=req.shed,
        )

    def submit(
        self,
        features: Union[Table, Mapping[str, Any]],
        timeout_ms: Optional[float] = None,
    ) -> "PendingPrediction":
        """Asynchronous prediction: enqueue and return a
        :class:`PendingPrediction` handle instead of blocking. The
        router's gray-failure path is built on this — it lets a caller
        stop WAITING on a dispatch (``handle.abandon()``) without being
        able to stop the device work, which is exactly the per-attempt
        deadline/hedging contract. Unlike :meth:`predict`, a full queue
        always raises the typed :class:`ServingOverloadError` (never
        sheds to the host path — shedding is a synchronous caller-thread
        degradation; an async caller wants the queue or a refusal)."""
        self._check_running()
        columns, rows = self._normalize(features)
        t0 = time.monotonic()
        timeout = (
            timeout_ms if timeout_ms is not None
            else self.config.default_timeout_ms
        )
        deadline = t0 + timeout / 1000.0 if timeout is not None else None
        req = ServingRequest(
            columns=columns, rows=rows, enqueued_at=t0, deadline=deadline
        )
        self._metrics.counter("requests")
        self._metrics.counter("rows", float(rows))
        if not self._batcher.offer(req):
            self._metrics.counter("rejected")
            raise ServingOverloadError(
                f"serving queue full ({self._batcher.max_queue_rows} rows); "
                "retry with backoff"
            )
        self._metrics.gauge("queue_depth", self._batcher.queue_depth)
        return PendingPrediction(self, req, t0)

    def _overloaded(self, req: ServingRequest, t0: float) -> ServingResponse:
        """Queue-full policy: shed to the per-stage host path in the
        caller's thread, or reject with the typed overload error. The
        deadline contract survives shedding: an already-expired request
        times out instead of blocking the caller on the slower path."""
        if not self.config.shed_on_overload:
            self._metrics.counter("rejected")
            raise ServingOverloadError(
                f"serving queue full ({self._batcher.max_queue_rows} rows); "
                "retry with backoff"
            )
        if req.deadline is not None and req.deadline <= time.monotonic():
            if req.claim_timeout_count():
                self._metrics.counter("timeouts")
            raise ServingTimeoutError(
                "request deadline expired at admission (queue saturated)"
            )
        self._metrics.counter("shed_requests")
        active = self._active
        # Same locking discipline as _serve_batch/_install: an SPMD
        # engine's per-stage transform still dispatches multi-device
        # programs, so shedding must not bypass the mesh lock (and the
        # dispatch stays visible to the FML302 trace audit).
        with self._dispatch_guard():
            from flinkml_tpu.parallel import dispatch as _dispatch

            if _dispatch.has_dispatch_observers():
                _dispatch.record_collective_dispatch(
                    "serving.shed", self._device_ids()
                )
            table = _transform_per_stage(active.model, Table(req.columns))
            result = {
                c: np.asarray(table.column(c)) for c in self._output_cols
            }
        latency_ms = (time.monotonic() - t0) * 1000.0
        self._record_latency(latency_ms)
        return ServingResponse(
            columns=result, version=active.version,
            latency_ms=latency_ms, shed=True,
        )

    def _normalize(
        self, features: Union[Table, Mapping[str, Any]]
    ) -> Tuple[Dict[str, np.ndarray], int]:
        if isinstance(features, Table):
            features = {n: features.column(n) for n in features.column_names}
        if set(features.keys()) != set(self._schema.keys()):
            raise ServingSchemaError(
                f"request columns {sorted(features.keys())} != schema "
                f"columns {sorted(self._schema.keys())}"
            )
        out: Dict[str, np.ndarray] = {}
        rows: Optional[int] = None
        for name, (dtype, trailing) in self._schema.items():
            a = np.asarray(features[name], dtype=dtype)
            if a.ndim == len(trailing):  # single row, leading axis omitted
                a = a[None]
            if a.shape[1:] != trailing:
                raise ServingSchemaError(
                    f"column {name!r} has trailing shape {a.shape[1:]}, "
                    f"schema expects {trailing}"
                )
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ServingSchemaError(
                    f"column {name!r} has {a.shape[0]} rows, others have "
                    f"{rows}"
                )
            out[name] = a
        if not rows:
            raise ServingSchemaError("empty request (zero rows)")
        if rows > self.config.max_batch_rows:
            raise ServingSchemaError(
                f"request has {rows} rows > max_batch_rows "
                f"{self.config.max_batch_rows}; split it client-side"
            )
        return out, rows

    # -- dispatcher --------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            batch, expired = self._batcher.next_batch(poll_s=0.02)
            for req in expired:
                if req.claim_timeout_count():
                    self._metrics.counter("timeouts")
                req.fail(ServingTimeoutError(
                    "request expired while queued (deadline passed before "
                    "dispatch)"
                ))
            if batch:
                self._serve_batch(batch)
            elif self._stop_event.is_set() and self._batcher.queue_depth == 0:
                return
            self._metrics.gauge("queue_depth", self._batcher.queue_depth)

    def _serve_batch(self, batch: List[BatchSegment]) -> None:
        active = self._active  # snapshot: in-flight work stays on it
        rows = sum(s.rows for s in batch)
        try:
            if faults.ACTIVE is not None:  # replica-kill seam (pool chaos)
                faults.fire("serving.replica", engine=self.name, rows=rows)
            cols = [s.columns for s in batch]
            packed = {
                name: (
                    np.concatenate([c[name] for c in cols])
                    if len(batch) > 1 else cols[0][name]
                )
                for name in self._schema
            }
            table = Table(packed)
            with self._dispatch_guard(), \
                    pipeline_fusion.precision_scope(self._policy):
                from flinkml_tpu.parallel import dispatch as _dispatch

                if _dispatch.has_dispatch_observers():
                    # The event carries the lock tokens this thread holds,
                    # so analysis.collectives.check_dispatch_trace can
                    # audit serving+training runs (FML302/FML303).
                    _dispatch.record_collective_dispatch(
                        f"{self.config.dispatch_tag or 'serving'}.batch",
                        self._device_ids(),
                    )
                (out,) = active.model.transform(table)
                host = {
                    c: np.asarray(out.column(c)) for c in self._output_cols
                }
        except BaseException as e:  # noqa: BLE001 — fail the batch, not the loop
            self._metrics.counter("errors")
            for seg in batch:
                seg.request.fail(e)
            return
        bucket = pipeline_fusion.row_bucket(rows)
        self._metrics.counter("batches")
        self._metrics.counter("batch_rows", float(rows))
        self._metrics.counter("batch_padded_rows", float(bucket))
        self._metrics.gauge("last_batch_occupancy", rows / bucket)
        now = time.monotonic()
        offset = 0
        completions = []
        for seg in batch:
            # Copies, not views: responses to different clients must not
            # alias one batch buffer (a client post-processing its arrays
            # in place would corrupt its batchmates' results).
            sliced = {
                c: host[c][offset:offset + seg.rows].copy() for c in host
            }
            offset += seg.rows
            outcome = seg.request.add_segment(
                seg.start, sliced, active.version, seg.rows
            )
            if outcome is None:
                continue  # more segments to come
            if outcome == "discarded":
                # The submitter abandoned this request (per-attempt
                # deadline or lost hedge race) — or it expired/failed —
                # while the batch was in flight: the straggler rows are
                # DISCARDED, never surfaced as a duplicate or (after a
                # hot swap) mis-versioned response.
                self._metrics.counter("discarded_results")
                continue
            if outcome == "mixed":
                # A hot swap landed between this request's segments: one
                # response must carry ONE version, so discard the partials
                # and re-dispatch the whole request on the new model.
                seg.request.reset_segments()
                self._metrics.counter("redispatched_for_version")
                if not self._batcher.requeue(seg.request):
                    seg.request.fail(EngineStoppedError(
                        "engine stopped while re-dispatching a request "
                        "split across a model swap"
                    ))
                continue
            completions.append((seg.request, *outcome))
        if completions:
            # Gauges first, completions second: a client reading stats
            # right after its predict() returns sees its own request
            # reflected. One lock acquisition + one sort for the batch.
            self._latency_window.record(*(
                (now - req.enqueued_at) * 1000.0
                for req, _, _ in completions
            ))
        for req, result, version in completions:
            if not req.complete(result, version):
                # The submitter abandoned this request (per-attempt
                # deadline or lost hedge race) while the batch was in
                # flight: the straggler result is DISCARDED here — it
                # must never surface as a duplicate or (after a hot
                # swap) mis-versioned response.
                self._metrics.counter("discarded_results")

    @contextlib.contextmanager
    def _dispatch_guard(self):
        """Multi-device serving programs time-share devices with training
        via the mesh lock; single-device programs (the fused executor's
        output) need no cross-thread lock — see module docstring. A
        ``config.device`` pin additionally routes every dispatch (and its
        input placement) to that device via ``jax.default_device`` — the
        replica pool's one-engine-per-device placement."""
        with contextlib.ExitStack() as stack:
            if self.config.device is not None:
                import jax

                stack.enter_context(jax.default_device(self.config.device))
            if self.config.mesh is not None:
                from flinkml_tpu.parallel.dispatch import local_execution_lock

                stack.enter_context(local_execution_lock(self.config.mesh))
            yield

    def _device_ids(self) -> Tuple[int, ...]:
        if self.config.mesh is not None:
            mesh = getattr(self.config.mesh, "mesh", self.config.mesh)
            return tuple(d.id for d in mesh.devices.flatten())
        if self.config.device is not None:
            return (self.config.device.id,)
        import jax

        return (jax.devices()[0].id,)

    def _record_latency(self, latency_ms: float) -> None:
        self._latency_window.record(latency_ms)

    def _check_running(self) -> None:
        if not self.running:
            raise EngineStoppedError(
                "serving engine is not running; call start()"
            )

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Point-in-time operational snapshot (the stats-endpoint dump)."""
        snap = self._metrics.snapshot()
        return {
            "name": self.name,
            "running": self.running,
            "active_version": self.active_version,
            "queue_depth": self._batcher.queue_depth,
            "queued_rows": self._batcher.queued_rows,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }

    def stats_text(self) -> str:
        """Prometheus-style exposition of the whole process registry
        (:meth:`flinkml_tpu.utils.metrics.MetricsRegistry.render_text`)."""
        from flinkml_tpu.utils.metrics import default_registry

        return default_registry().render_text()


class PendingPrediction:
    """Handle to one request submitted via :meth:`ServingEngine.submit`.

    The handle owns the CLIENT side of the request only: the caller can
    wait on it, read the response once done, or ``abandon()`` it — which
    stops the waiting, releases the request's queued rows at the
    batcher's next sweep, and guarantees (via :meth:`ServingRequest
    .complete`'s CAS) that a straggler batch result is discarded rather
    than published. The device work itself is not interruptible; that is
    the point — gray-failure defense is about not *waiting* on a stalled
    replica, not about pretending its work can be cancelled."""

    def __init__(self, engine: ServingEngine, request: ServingRequest,
                 t0: float):
        self.engine = engine
        self.request = request
        self.t0 = t0

    @property
    def done(self) -> bool:
        return self.request.done.is_set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self.request.done.wait(timeout_s)

    def abandon(self) -> bool:
        """Stop waiting (CAS — see :meth:`ServingRequest.abandon`).
        True for exactly one abandoner; False when a result or error
        already landed."""
        if self.request.abandon():
            self.engine._metrics.counter("abandoned")
            return True
        return False

    def response(self) -> ServingResponse:
        """The completed response (call after :meth:`wait` returned
        True); raises the request's typed error if it failed, and
        :class:`ServingTimeoutError` if it was abandoned."""
        req = self.request
        if not req.done.is_set():
            raise RuntimeError("pending prediction has not completed")
        if req.abandoned:
            raise ServingTimeoutError(
                "request was abandoned by its submitter"
            )
        if req.error is not None:
            raise req.error
        return ServingResponse(
            columns=req.result, version=req.version,
            latency_ms=(time.monotonic() - self.t0) * 1000.0,
            shed=req.shed,
        )


def _all_buckets_up_to(max_rows: int) -> List[int]:
    buckets = []
    b = pipeline_fusion.MIN_ROW_BUCKET
    top = pipeline_fusion.row_bucket(max_rows)
    while b <= top:
        buckets.append(b)
        b *= 2
    return buckets


def _transform_per_stage(model: Any, table: Table) -> Table:
    """The host (unfused) path: chain each stage's own ``transform``.
    Identical semantics to ``PipelineModel.transform`` with fusion
    disabled, without touching the process-wide fusion switch (other
    threads may be mid-fused-dispatch)."""
    stages = getattr(model, "stages", None)
    if stages is None:
        (out,) = model.transform(table)
        return out
    for stage in stages:
        (table,) = stage.transform(table)
    return table
