"""Typed serving errors — the admission-control and registry contract.

Every rejection the online path can hand a client is a *named* error, so
callers can branch on failure mode (retry on overload, surface timeouts,
page on integrity failures) instead of parsing messages. The model-data
integrity error lives with the persistence layer
(:class:`flinkml_tpu.io.read_write.ModelIntegrityError`) and is re-exported
here because the registry is where operators meet it.
"""

from __future__ import annotations

from flinkml_tpu.io.read_write import ModelIntegrityError  # noqa: F401


class ServingError(RuntimeError):
    """Base class of every serving-runtime error."""


class ServingOverloadError(ServingError):
    """The request was rejected at admission: the bounded request queue
    is full and shedding to the host path is disabled
    (``ServingConfig.shed_on_overload=False``). Back off and retry."""


class ServingTimeoutError(ServingError, TimeoutError):
    """The request's deadline expired before a result was produced —
    either while queued (the dispatcher rejects expired requests at
    batch formation) or while waiting on an in-flight batch."""


class EngineStoppedError(ServingError):
    """The engine is not running (never started, or stopped); queued
    requests are failed with this at shutdown rather than left hanging."""


class ServingSchemaError(ServingError, ValueError):
    """A request's columns do not match the engine's input schema (names,
    trailing shapes) fixed by the warmup example at load time."""


class ServingMemoryError(ServingError):
    """A model was refused at load/swap time because its estimated
    per-device HBM footprint (learned arrays at the engine's precision
    tier, plus batch buffers at the largest dispatch bucket — see
    :func:`flinkml_tpu.analysis.memory.estimate_serving_bytes`) exceeds
    ``ServingConfig.hbm_budget_bytes``. Raised BEFORE the active-model
    flip, so a follower's refused swap keeps the previous model serving
    — the ``refuse_nonfinite`` idiom applied to capacity."""


class SLOAdmissionError(ServingOverloadError):
    """A multi-tenant request was refused at CLASS admission: its SLO
    class's share of pool capacity (``SLOClass.max_queue_share``) is
    fully in flight. A :class:`ServingOverloadError` subclass — the
    remedy is the same (back off and retry) — but named so a batch
    client can tell "my class budget is spent" from "the whole pool is
    saturated": the former is working as designed (the interactive tier
    keeps its headroom), the latter is a capacity page."""


class PoolUnavailableError(ServingError):
    """The replica pool has no healthy replica left to route to — every
    replica is unhealthy or draining. Distinct from
    :class:`ServingOverloadError` (healthy replicas exist but every
    bounded queue is full): this one pages, that one backs off."""


class RegistryError(RuntimeError):
    """Base class of model-registry errors."""


class ModelVersionNotFoundError(RegistryError, KeyError):
    """The requested model version does not exist in the registry (or the
    registry has no published versions yet)."""


class DeltaChainError(RegistryError):
    """An incremental (delta) version cannot be resolved to a model: its
    base version is pruned, a fingerprint along the chain does not match
    the state it claims to patch, or the base is not delta-capable. The
    message names the exact broken link (``version N -> base M``) — the
    registry NEVER silently falls back to a stale or fresh model (the
    ``restore_latest`` contract, extended to delta chains)."""


__all__ = [
    "ModelIntegrityError",
    "PoolUnavailableError",
    "SLOAdmissionError",
    "ServingError",
    "ServingOverloadError",
    "ServingTimeoutError",
    "EngineStoppedError",
    "ServingSchemaError",
    "ServingMemoryError",
    "RegistryError",
    "ModelVersionNotFoundError",
    "DeltaChainError",
]
