"""Per-replica health: states, degradation policy, and the ledger.

The pool's overload story degrades **by replica, not globally** (ROADMAP
item 3): one replica tripping its queue bound or failing its dispatches
is taken out of rotation while the rest of the pool keeps serving. Three
states:

- ``HEALTHY`` — in rotation.
- ``DRAINING`` — temporarily out of rotation after tripping its queue
  bound ``overload_trip`` times in a row; the engine keeps draining its
  queue, and the replica rejoins automatically once its backlog falls
  under ``drain_low_water`` of capacity (checked inline on every routing
  decision — no poller thread).
- ``SLOW`` — quarantined by the gray-failure guard
  (:class:`~flinkml_tpu.serving.grayfail.GrayFailGuard`): the replica is
  alive and passing dispatches but a robust latency-outlier test (its
  attempt p99 vs the healthy-sibling median, MAD-based) says it is
  dragging pool tail latency. Removed from routing WITHOUT being
  killed; the guard probes it with low-rate canary dispatches and
  rejoins it (:meth:`ReplicaHealth.clear_slow`) on sustained recovery.
  A SLOW replica does NOT count as healthy for the autoscaler, so
  quarantine below ``min_replicas`` triggers replacement.
- ``UNHEALTHY`` — failed hard (``max_consecutive_errors`` dispatch
  failures, e.g. the ``serving.replica`` fault seam killing it): the
  pool retires it (stop without drain — queued requests fail fast and
  the router re-runs them on healthy replicas) and never routes to it
  again until :meth:`ReplicaHealth.revive`.

Transitions are CAS-style under one lock so racing router threads agree
on exactly one retirement per replica.

The ledger also keeps a per-ATTEMPT latency ring (:meth:`record_attempt`
/ :meth:`attempt_p99`): successful attempt latencies plus CENSORED
observations for abandoned attempts (recorded at the abandonment budget
— a stalled dispatch whose true latency is unknown still counts as "at
least this slow"). This ring, not the engine's completion window, is
what the gray-failure outlier test reads: it sees what the ROUTER
experienced, including the dispatches it gave up on.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import math
import threading
import time
from typing import Optional


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    DRAINING = "draining"
    SLOW = "slow"
    UNHEALTHY = "unhealthy"


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Degradation thresholds (see module docstring)."""

    #: Dispatch failures in a row before the replica is retired.
    max_consecutive_errors: int = 1
    #: Queue-full refusals in a row before the replica drains.
    overload_trip: int = 8
    #: Fraction of ``max_queue_rows`` the backlog must fall under for a
    #: DRAINING replica to rejoin rotation.
    drain_low_water: float = 0.25


class ReplicaHealth:
    """One replica's health ledger. Thread-safe; shared by every router
    thread touching the replica."""

    def __init__(self, name: str, policy: Optional[HealthPolicy] = None):
        self.name = name
        self.policy = policy or HealthPolicy()
        self._lock = threading.Lock()
        self._state = ReplicaState.HEALTHY
        self._consecutive_errors = 0
        self._consecutive_overloads = 0
        self._last_error: Optional[BaseException] = None
        self._state_since = time.monotonic()
        #: Rows submitted to this replica and not yet settled — the
        #: router's least-outstanding-rows balance key.
        self.outstanding_rows = 0
        #: EWMA of observed ms per served row (queue wait included);
        #: feeds the router's deadline-aware replica ordering.
        self.ewma_ms_per_row: Optional[float] = None
        #: Per-attempt latency ring (successes + censored abandonments)
        #: — the gray-failure outlier test's input. Guarded by ``_lock``.
        self._attempt_ms: collections.deque = collections.deque(maxlen=256)
        self._abandoned_attempts = 0

    # -- state -------------------------------------------------------------
    @property
    def state(self) -> ReplicaState:
        return self._state

    @property
    def last_error(self) -> Optional[BaseException]:
        return self._last_error

    def _transition(self, state: ReplicaState) -> None:
        self._state = state
        self._state_since = time.monotonic()

    def routable(self) -> bool:
        return self._state is ReplicaState.HEALTHY

    # -- router accounting -------------------------------------------------
    def submit(self, rows: int) -> None:
        with self._lock:
            self.outstanding_rows += rows

    def settle(self, rows: int) -> None:
        with self._lock:
            self.outstanding_rows = max(0, self.outstanding_rows - rows)

    def estimated_wait_ms(self) -> Optional[float]:
        """Outstanding backlog × observed per-row latency, or None before
        any observation. An ESTIMATE for ordering/deadline hints only —
        never a reason to hard-reject on its own."""
        with self._lock:
            if self.ewma_ms_per_row is None:
                return None
            return self.outstanding_rows * self.ewma_ms_per_row

    # -- outcomes ----------------------------------------------------------
    def on_success(self, rows: int, latency_ms: float) -> None:
        with self._lock:
            self._consecutive_errors = 0
            self._consecutive_overloads = 0
            if rows > 0:
                per_row = latency_ms / rows
                self.ewma_ms_per_row = (
                    per_row if self.ewma_ms_per_row is None
                    else 0.8 * self.ewma_ms_per_row + 0.2 * per_row
                )

    # -- gray-failure signal (per-attempt latency ring) --------------------
    def record_attempt(self, latency_ms: float, abandoned: bool = False
                       ) -> None:
        """Record what one ROUTER attempt experienced on this replica:
        the attempt latency on success, or a censored observation (the
        abandonment budget — "at least this slow") when the router gave
        up waiting."""
        with self._lock:
            self._attempt_ms.append(float(latency_ms))
            if abandoned:
                self._abandoned_attempts += 1

    def attempt_p99(self, min_samples: int = 1) -> Optional[float]:
        """p99 over the attempt ring, or None below ``min_samples``."""
        with self._lock:
            n = len(self._attempt_ms)
            if n < max(1, min_samples):
                return None
            ordered = sorted(self._attempt_ms)
            return ordered[min(n - 1, math.ceil(0.99 * n) - 1)]

    def recent_attempt_p99(self, window: int,
                           min_samples: int = 1) -> Optional[float]:
        """p99 over only the newest ``window`` ring entries (None below
        ``min_samples`` total). The quarantine REJOIN decision reads
        this: a recovered replica's stall-era canary observations would
        otherwise hold the whole-ring p99 high until they aged out of
        the ring — hundreds of probes after the stall actually cleared."""
        with self._lock:
            if len(self._attempt_ms) < max(1, min_samples):
                return None
            recent = sorted(list(self._attempt_ms)[-max(1, window):])
            n = len(recent)
            return recent[min(n - 1, math.ceil(0.99 * n) - 1)]

    def mark_slow(self) -> bool:
        """HEALTHY -> SLOW (CAS): quarantine a latency outlier without
        killing it. True for exactly one caller; False from any other
        state (a DRAINING/UNHEALTHY replica already has a stronger
        verdict). Clears the attempt ring: the rejoin decision must read
        only POST-quarantine (canary) evidence, not the stall that
        caused the quarantine."""
        with self._lock:
            if self._state is not ReplicaState.HEALTHY:
                return False
            self._attempt_ms.clear()
            self._transition(ReplicaState.SLOW)
            return True

    def clear_slow(self) -> bool:
        """SLOW -> HEALTHY (CAS) on sustained canary recovery. Clears
        the attempt ring: the stall-era censored observations would
        otherwise immediately re-trip the outlier test on rejoin."""
        with self._lock:
            if self._state is not ReplicaState.SLOW:
                return False
            self._attempt_ms.clear()
            self._abandoned_attempts = 0
            self._transition(ReplicaState.HEALTHY)
            return True

    def force_unhealthy(self, error: BaseException) -> bool:
        """Administrative retirement (the guard escalating a quarantine
        that never recovered): any state except UNHEALTHY -> UNHEALTHY.
        True for exactly one caller — the same exactly-one-retirement
        CAS as :meth:`on_error`."""
        with self._lock:
            if self._state is ReplicaState.UNHEALTHY:
                return False
            self._last_error = error
            self._transition(ReplicaState.UNHEALTHY)
            return True

    def state_age_s(self) -> float:
        with self._lock:
            return time.monotonic() - self._state_since

    def on_overload(self) -> bool:
        """Record one queue-full refusal; True when this trip moved the
        replica HEALTHY -> DRAINING (the caller logs/metrics it)."""
        with self._lock:
            self._consecutive_overloads += 1
            if (
                self._state is ReplicaState.HEALTHY
                and self._consecutive_overloads >= self.policy.overload_trip
            ):
                self._transition(ReplicaState.DRAINING)
                return True
            return False

    def on_error(self, error: BaseException) -> bool:
        """Record one dispatch failure; True when this failure crossed
        the threshold and the replica must be RETIRED (exactly one caller
        gets True — the CAS the pool's single-retire relies on)."""
        with self._lock:
            self._last_error = error
            self._consecutive_errors += 1
            if (
                self._state is not ReplicaState.UNHEALTHY
                and self._consecutive_errors
                >= self.policy.max_consecutive_errors
            ):
                self._transition(ReplicaState.UNHEALTHY)
                return True
            return False

    def maybe_rejoin(self, queued_rows: int, max_queue_rows: int) -> bool:
        """Inline DRAINING -> HEALTHY recovery check (called by the
        router on every pass over the replicas)."""
        with self._lock:
            if self._state is not ReplicaState.DRAINING:
                return False
            if queued_rows <= max_queue_rows * self.policy.drain_low_water:
                self._transition(ReplicaState.HEALTHY)
                self._consecutive_overloads = 0
                return True
            return False

    def revive(self) -> None:
        """Operator-driven UNHEALTHY -> HEALTHY (after the pool restarted
        the engine). Resets the LATENCY/backlog stats too: the revived
        engine starts with an empty queue and fresh programs, so ranking
        it by its pre-failure EWMA (often inflated by the very death
        throes that retired it) would mis-order it until the stale
        history washed out — the pool re-seeds from healthy siblings
        right after (:meth:`seed_ewma`)."""
        with self._lock:
            self._consecutive_errors = 0
            self._consecutive_overloads = 0
            self._last_error = None
            self.outstanding_rows = 0
            self.ewma_ms_per_row = None
            self._attempt_ms.clear()
            self._abandoned_attempts = 0
            self._transition(ReplicaState.HEALTHY)

    def seed_ewma(self, ms_per_row: Optional[float]) -> None:
        """Seed the latency estimate of a replica that has served
        nothing yet (fresh scale-up, or just revived) from its healthy
        siblings' median, so the router's deadline ordering treats it as
        a known-latency candidate immediately instead of letting it
        settle late. Never clobbers a real observation."""
        if ms_per_row is None:
            return
        with self._lock:
            if self.ewma_ms_per_row is None:
                self.ewma_ms_per_row = float(ms_per_row)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "state": self._state.value,
                "state_age_s": round(time.monotonic() - self._state_since, 3),
                "outstanding_rows": self.outstanding_rows,
                "consecutive_errors": self._consecutive_errors,
                "consecutive_overloads": self._consecutive_overloads,
                "ewma_ms_per_row": self.ewma_ms_per_row,
                "attempt_samples": len(self._attempt_ms),
                "abandoned_attempts": self._abandoned_attempts,
                "last_error": (
                    repr(self._last_error) if self._last_error else None
                ),
            }
