"""Request routing over a replica pool: balance, admit, fail over.

The router is the pool's front door. Per request:

1. **Admission** is deadline-aware: a request whose deadline already
   passed is refused with the typed timeout BEFORE it occupies any
   queue, and replicas are ordered so ones whose estimated backlog
   (outstanding rows × observed ms/row EWMA) fits the remaining budget
   come first — the estimate orders candidates, it never hard-rejects
   (an EWMA is a hint, not a promise).
2. **Balance** is least-outstanding-rows: among routable replicas the
   one with the fewest submitted-but-unsettled rows wins — cheap,
   greedy, and (unlike round-robin) automatically biased away from slow
   or draining-adjacent replicas because their backlog settles late.
3. **Failover**: a replica whose dispatch fails (a killed replica's
   batches raise, a stopped engine refuses) reports to its health
   ledger — crossing the threshold retires it via the pool callback —
   and the request is re-run on the next candidate. Transforms are pure,
   so a retry cannot double-apply anything; a request is retried at most
   once per replica. Queue-full refusals fail over the same way without
   counting as errors (and trip the replica into DRAINING after enough
   consecutive refusals — per-replica degradation, not a global brownout).

Typed outcomes: client mistakes (:class:`ServingSchemaError`) and
deadline expiry (:class:`ServingTimeoutError`) propagate immediately —
they would fail identically on every replica. When every candidate was
tried: all-queues-full is :class:`ServingOverloadError` (back off and
retry), no-routable-replica is :class:`PoolUnavailableError` (page).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence

from flinkml_tpu.serving.errors import (
    PoolUnavailableError,
    ServingOverloadError,
    ServingSchemaError,
    ServingTimeoutError,
)
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("serving.router")


class Router:
    """Stateless-per-request router over the pool's replicas. ``replicas``
    is a live sequence of objects with ``.name``, ``.engine`` and
    ``.health`` (:class:`~flinkml_tpu.serving.health.ReplicaHealth`);
    ``rows_of`` estimates a request's row count for balance accounting;
    ``on_retire(replica, error)`` is the pool's retirement hook (invoked
    exactly once per replica, from whichever router thread crossed the
    error threshold)."""

    def __init__(
        self,
        replicas: Sequence[Any],
        rows_of: Callable[[Any], int],
        metrics_group,
        on_retire: Optional[Callable[[Any, BaseException], None]] = None,
    ):
        self._replicas = replicas
        self._rows_of = rows_of
        self._metrics = metrics_group
        self._on_retire = on_retire

    # -- candidate selection -----------------------------------------------
    def _candidates(self, tried: set,
                    model_id: Optional[str] = None) -> List[Any]:
        out = []
        for replica in list(self._replicas):  # snapshot: scaling mutates
            if replica.name in tried:
                continue
            if (model_id is not None
                    and getattr(replica, "model_id", None) != model_id):
                continue  # multi-model pools: route within the model
            health = replica.health
            if not health.routable():
                # Inline DRAINING -> HEALTHY recovery: rejoin once the
                # backlog fell under the policy's low-water mark.
                health.maybe_rejoin(
                    replica.engine._batcher.queued_rows,
                    replica.engine.config.max_queue_rows,
                )
                if not health.routable():
                    continue
            out.append(replica)
        return out

    def _order(self, candidates: List[Any],
               remaining_ms: Optional[float]) -> List[Any]:
        def backlog(r):
            return r.health.outstanding_rows

        ordered = sorted(candidates, key=backlog)
        if remaining_ms is None:
            return ordered
        fits, tight = [], []
        for r in ordered:
            est = r.health.estimated_wait_ms()
            (fits if est is None or est <= remaining_ms else tight).append(r)
        return fits + tight

    # -- the request path --------------------------------------------------
    def predict(self, features: Any, timeout_ms: Optional[float] = None,
                model_id: Optional[str] = None):
        t0 = time.monotonic()
        deadline = t0 + timeout_ms / 1000.0 if timeout_ms is not None else None
        rows = self._rows_of(features)
        self._metrics.counter("routed_requests")
        self._metrics.counter("routed_rows", float(rows))
        tried: set = set()
        last_overload: Optional[BaseException] = None
        last_failure: Optional[BaseException] = None
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                self._metrics.counter("admission_timeouts")
                raise ServingTimeoutError(
                    f"request deadline ({timeout_ms}ms) expired at pool "
                    "admission"
                )
            remaining_ms = (
                None if deadline is None
                else (deadline - time.monotonic()) * 1000.0
            )
            candidates = self._order(
                self._candidates(tried, model_id), remaining_ms
            )
            if not candidates:
                break
            replica = candidates[0]
            health = replica.health
            health.submit(rows)
            attempt_t0 = time.monotonic()
            try:
                resp = replica.engine.predict(
                    features, timeout_ms=remaining_ms
                )
            except ServingSchemaError:
                raise  # client mistake: identical on every replica
            except ServingTimeoutError:
                raise  # the deadline contract outranks failover
            except ServingOverloadError as e:
                last_overload = e
                tried.add(replica.name)
                self._metrics.counter("overload_reroutes")
                if health.on_overload():
                    self._metrics.counter("replicas_draining")
                    _log.warning(
                        "replica %s tripped its queue bound -> DRAINING",
                        replica.name,
                    )
                continue
            except BaseException as e:  # noqa: BLE001 — replica failure
                last_failure = e
                tried.add(replica.name)
                self._metrics.counter("failovers")
                if health.on_error(e):
                    _log.warning(
                        "replica %s failed dispatch (%r) -> UNHEALTHY",
                        replica.name, e,
                    )
                    if self._on_retire is not None:
                        self._on_retire(replica, e)
                continue
            finally:
                health.settle(rows)
            # Per-ATTEMPT latency: time spent failing over on earlier
            # replicas must not inflate this replica's backlog estimate.
            health.on_success(rows, (time.monotonic() - attempt_t0) * 1000.0)
            if tried:
                self._metrics.counter("retried_successes")
            return resp
        if last_overload is not None:
            self._metrics.counter("pool_overloads")
            raise ServingOverloadError(
                "every healthy replica's queue is full; retry with backoff"
            ) from last_overload
        self._metrics.counter("pool_unavailable")
        raise PoolUnavailableError(
            "no healthy replica available"
            + (f" (last failure: {last_failure!r})" if last_failure else "")
        ) from last_failure
