"""Request routing over a replica pool: balance, admit, fail over, hedge.

The router is the pool's front door. Per request:

1. **Admission** is deadline-aware: a request whose deadline already
   passed is refused with the typed timeout BEFORE it occupies any
   queue, and replicas are ordered so ones whose estimated backlog
   (outstanding rows × observed ms/row EWMA) fits the remaining budget
   come first — the estimate orders candidates, it never hard-rejects
   (an EWMA is a hint, not a promise). Untimed requests inherit the
   pool-level ``default_timeout_ms`` so a stalled replica can never
   hold a caller forever.
2. **Balance** is least-outstanding-rows: among routable replicas the
   one with the fewest submitted-but-unsettled rows wins — cheap,
   greedy, and (unlike round-robin) automatically biased away from slow
   or draining-adjacent replicas because their backlog settles late.
3. **Failover**: a replica whose dispatch fails (a killed replica's
   batches raise, a stopped engine refuses) reports to its health
   ledger — crossing the threshold retires it via the pool callback —
   and the request is re-run on the next candidate. Transforms are pure,
   so a retry cannot double-apply anything; a request is retried at most
   once per replica. Queue-full refusals fail over the same way without
   counting as errors (and trip the replica into DRAINING after enough
   consecutive refusals — per-replica degradation, not a global brownout).
4. **Gray-failure containment** (when a
   :class:`~flinkml_tpu.serving.grayfail.GrayFailPolicy` is wired in):

   - *Per-attempt deadlines with true abandonment*: each dispatch gets
     a budget of healthy-sibling attempt-p99 median ×
     ``deadline_multiplier`` (floored at ``attempt_floor_ms``). A
     dispatch exceeding it is ABANDONED — the router stops waiting and
     fails over, the request's queued rows release at the batcher's
     next sweep, and the abandoned attempt's late straggler result is
     discarded by the request's terminal-transition CAS, so it can
     never surface as a duplicate or (across a hot swap) mis-versioned
     response. The abandonment is recorded in the replica's attempt
     ring as a CENSORED observation at the budget value — the
     quarantine guard's evidence.
   - *Hedged requests*: transforms are pure and idempotent, so a
     request whose first attempt exceeds the hedge threshold
     (sibling p99 × ``hedge_multiplier``, floored) is speculatively
     re-dispatched to the next-best replica. First completion wins;
     the loser is abandoned (cancelled at the queue, straggler result
     discarded). Hedging duplicates DISPATCH work only — admission
     budgets (SLO ledgers) are charged per request, upstream of the
     router, so a hedge is never double-counted.

Typed outcomes: client mistakes (:class:`ServingSchemaError`) and
deadline expiry (:class:`ServingTimeoutError`) propagate immediately —
they would fail identically on every replica. When every candidate was
tried: all-queues-full is :class:`ServingOverloadError` (back off and
retry), no-routable-replica is :class:`PoolUnavailableError` (page).
"""

from __future__ import annotations

import statistics
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from flinkml_tpu.serving.errors import (
    PoolUnavailableError,
    ServingOverloadError,
    ServingSchemaError,
    ServingTimeoutError,
)
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("serving.router")

#: Grace the engine's synchronous path has always given an IN-FLIGHT
#: batch past the request deadline; the router's await loop honors the
#: same allowance before raising the typed timeout.
_DEADLINE_GRACE_S = 0.25

#: Cap on one await-loop sleep: the race event wakes the loop on any
#: attempt's terminal transition, but an attempt that completes in the
#: narrow window before its event is wired would otherwise sleep a full
#: budget.
_MAX_WAIT_SLICE_S = 0.05


class _Attempt:
    """One in-flight dispatch of a request on one replica."""

    __slots__ = ("replica", "pending", "t0", "abandon_at", "hedge")

    def __init__(self, replica, pending, t0, abandon_at, hedge):
        self.replica = replica
        self.pending = pending
        self.t0 = t0
        self.abandon_at = abandon_at  # monotonic, None = no budget
        self.hedge = hedge


class Router:
    """Stateless-per-request router over the pool's replicas. ``replicas``
    is a live sequence of objects with ``.name``, ``.engine`` and
    ``.health`` (:class:`~flinkml_tpu.serving.health.ReplicaHealth`);
    ``rows_of`` estimates a request's row count for balance accounting;
    ``on_retire(replica, error)`` is the pool's retirement hook (invoked
    exactly once per replica, from whichever router thread crossed the
    error threshold). ``grayfail`` enables per-attempt abandonment and
    hedging; ``default_timeout_ms`` is the finite deadline untimed
    requests inherit; ``pool_name`` names the labeled hedge-outcome
    metric family."""

    def __init__(
        self,
        replicas: Sequence[Any],
        rows_of: Callable[[Any], int],
        metrics_group,
        on_retire: Optional[Callable[[Any, BaseException], None]] = None,
        grayfail: Optional[Any] = None,
        default_timeout_ms: Optional[float] = None,
        pool_name: Optional[str] = None,
    ):
        self._replicas = replicas
        self._rows_of = rows_of
        self._metrics = metrics_group
        self._on_retire = on_retire
        self._grayfail = grayfail
        self._default_timeout_ms = default_timeout_ms
        self._pool_name = pool_name

    def _hedge_outcome(self, outcome: str) -> None:
        self._metrics.counter(f"hedges_{outcome}")
        if self._pool_name is not None:
            metrics.group(
                f"serving.{self._pool_name}.hedges",
                labels={"outcome": outcome},
            ).counter("total")

    # -- candidate selection -----------------------------------------------
    def _candidates(self, tried: set,
                    model_id: Optional[str] = None) -> List[Any]:
        out = []
        for replica in list(self._replicas):  # snapshot: scaling mutates
            if replica.name in tried:
                continue
            if (model_id is not None
                    and getattr(replica, "model_id", None) != model_id):
                continue  # multi-model pools: route within the model
            health = replica.health
            if not health.routable():
                # Inline DRAINING -> HEALTHY recovery: rejoin once the
                # backlog fell under the policy's low-water mark. (SLOW
                # replicas rejoin through the guard's canary path, never
                # here.)
                health.maybe_rejoin(
                    replica.engine._batcher.queued_rows,
                    replica.engine.config.max_queue_rows,
                )
                if not health.routable():
                    continue
            out.append(replica)
        return out

    def _order(self, candidates: List[Any],
               remaining_ms: Optional[float]) -> List[Any]:
        def backlog(r):
            return r.health.outstanding_rows

        ordered = sorted(candidates, key=backlog)
        if remaining_ms is None:
            return ordered
        fits, tight = [], []
        for r in ordered:
            est = r.health.estimated_wait_ms()
            (fits if est is None or est <= remaining_ms else tight).append(r)
        return fits + tight

    # -- gray-failure budgets ----------------------------------------------
    def _sibling_p99_ms(self, exclude: Optional[Any]) -> Optional[float]:
        """Median of the routable replicas' attempt-ring p99s (excluding
        ``exclude``) — the robust 'what do healthy siblings look like'
        statistic the attempt budget and hedge threshold derive from.
        None until enough siblings have enough samples."""
        gf = self._grayfail
        vals = []
        for r in list(self._replicas):
            if r is exclude or not r.health.routable():
                continue
            p = r.health.attempt_p99(min_samples=gf.min_attempt_samples)
            if p is not None:
                vals.append(p)
        if not vals:
            return None
        return float(statistics.median(vals))

    def _attempt_budget_s(self, replica: Any) -> Optional[float]:
        gf = self._grayfail
        if gf is None or not gf.abandon:
            return None
        sib = self._sibling_p99_ms(replica)
        if sib is None:
            return None  # cold pool: no evidence, no abandonment
        budget_ms = max(
            gf.attempt_floor_ms, sib * gf.resolved_deadline_multiplier()
        )
        return budget_ms / 1000.0

    def _hedge_delay_s(self) -> Optional[float]:
        gf = self._grayfail
        if gf is None or not gf.hedge:
            return None
        sib = self._sibling_p99_ms(None)
        if sib is None:
            return None
        return max(gf.hedge_floor_ms, sib * gf.hedge_multiplier) / 1000.0

    # -- the request path --------------------------------------------------
    def predict(self, features: Any, timeout_ms: Optional[float] = None,
                model_id: Optional[str] = None):
        if timeout_ms is None:
            timeout_ms = self._default_timeout_ms
        t0 = time.monotonic()
        deadline = t0 + timeout_ms / 1000.0 if timeout_ms is not None else None
        rows = self._rows_of(features)
        self._metrics.counter("routed_requests")
        self._metrics.counter("routed_rows", float(rows))
        tried: set = set()
        state = {"overload": None, "failure": None, "abandoned": 0}
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                self._metrics.counter("admission_timeouts")
                raise ServingTimeoutError(
                    f"request deadline ({timeout_ms}ms) expired at pool "
                    "admission"
                )
            remaining_ms = (
                None if deadline is None
                else (deadline - time.monotonic()) * 1000.0
            )
            candidates = self._order(
                self._candidates(tried, model_id), remaining_ms
            )
            if not candidates:
                break
            resp = self._run_attempts(
                candidates, features, rows, deadline, tried, state
            )
            if resp is not None:
                if tried:
                    self._metrics.counter("retried_successes")
                return resp
        if state["overload"] is not None:
            self._metrics.counter("pool_overloads")
            raise ServingOverloadError(
                "every healthy replica's queue is full; retry with backoff"
            ) from state["overload"]
        self._metrics.counter("pool_unavailable")
        detail = ""
        if state["failure"] is not None:
            detail = f" (last failure: {state['failure']!r})"
        elif state["abandoned"]:
            detail = (
                f" ({state['abandoned']} dispatch(es) abandoned past their "
                "attempt budget — every candidate looks stalled)"
            )
        raise PoolUnavailableError(
            "no healthy replica available" + detail
        ) from state["failure"]

    # -- one round: primary attempt + optional hedge -------------------------
    def _dispatch(self, replica: Any, features: Any, rows: int,
                  deadline: Optional[float], race: threading.Event,
                  tried: set, state: dict, hedge: bool) -> Optional[_Attempt]:
        """Submit one attempt. Returns the live attempt, or None when the
        submit itself was refused/failed (recorded in ``tried``/``state``
        — the caller moves on)."""
        health = replica.health
        health.submit(rows)
        now = time.monotonic()
        remaining_ms = None if deadline is None else max(
            0.0, (deadline - now) * 1000.0
        )
        try:
            pending = replica.engine.submit(features, timeout_ms=remaining_ms)
        except ServingSchemaError:
            health.settle(rows)
            raise  # client mistake: identical on every replica
        except ServingOverloadError as e:
            health.settle(rows)
            state["overload"] = e
            tried.add(replica.name)
            self._metrics.counter("overload_reroutes")
            if health.on_overload():
                self._metrics.counter("replicas_draining")
                _log.warning(
                    "replica %s tripped its queue bound -> DRAINING",
                    replica.name,
                )
            return None
        except BaseException as e:  # noqa: BLE001 — replica failure
            health.settle(rows)
            self._record_failure(replica, e, tried, state)
            return None
        pending.request.race = race
        budget_s = self._attempt_budget_s(replica)
        abandon_at = None if budget_s is None else now + budget_s
        return _Attempt(replica, pending, now, abandon_at, hedge)

    def _record_failure(self, replica: Any, error: BaseException,
                        tried: set, state: dict) -> None:
        state["failure"] = error
        tried.add(replica.name)
        self._metrics.counter("failovers")
        if replica.health.on_error(error):
            _log.warning(
                "replica %s failed dispatch (%r) -> UNHEALTHY",
                replica.name, error,
            )
            if self._on_retire is not None:
                self._on_retire(replica, error)

    def _abandon_attempt(self, a: _Attempt, rows: int, tried: set,
                         state: dict) -> bool:
        """Per-attempt budget expiry: stop waiting, record the censored
        observation, fail over. False when the attempt completed in the
        race window (the caller finalizes it normally instead)."""
        if not a.pending.abandon():
            return False
        health = a.replica.health
        health.settle(rows)
        budget_ms = (a.abandon_at - a.t0) * 1000.0
        health.record_attempt(budget_ms, abandoned=True)
        tried.add(a.replica.name)
        state["abandoned"] += 1
        self._metrics.counter("abandoned_attempts")
        _log.warning(
            "abandoned dispatch on replica %s after %.0fms attempt budget "
            "(failing over; straggler result will be discarded)",
            a.replica.name, budget_ms,
        )
        return True

    def _cancel_loser(self, a: _Attempt, rows: int) -> None:
        """Another attempt won the race: cancel this one at the queue and
        discard whatever it may still produce. Its elapsed time is a
        LOWER BOUND on its latency — recorded censored, so a habitually
        slow replica keeps accumulating quarantine evidence even when
        hedges keep saving its requests."""
        a.pending.abandon()
        a.replica.health.settle(rows)
        a.replica.health.record_attempt(
            (time.monotonic() - a.t0) * 1000.0, abandoned=True
        )
        if a.hedge:
            self._hedge_outcome("lost")

    def _run_attempts(self, candidates: List[Any], features: Any, rows: int,
                      deadline: Optional[float], tried: set,
                      state: dict) -> Optional[Any]:
        """Dispatch to ``candidates[0]`` and race it against per-attempt
        budgets, the overall deadline, and (past the hedge threshold) one
        speculative re-dispatch to the next-best candidate. Returns the
        winning response, or None when every live attempt failed or was
        abandoned (the outer loop re-selects over the updated tried-set).
        """
        race = threading.Event()
        attempts: List[_Attempt] = []
        winner: Optional[_Attempt] = None
        alternates = list(candidates[1:])
        first = self._dispatch(
            candidates[0], features, rows, deadline, race, tried, state,
            hedge=False,
        )
        if first is None:
            return None
        attempts.append(first)
        hedge_delay = self._hedge_delay_s() if alternates else None
        hedge_at = None if hedge_delay is None else first.t0 + hedge_delay
        try:
            while attempts:
                now = time.monotonic()
                # 1) Completions first: a result that landed outranks any
                #    budget that expired in the same slice.
                for a in list(attempts):
                    if not a.pending.request.done.is_set():
                        continue
                    attempts.remove(a)
                    health = a.replica.health
                    health.settle(rows)
                    err = a.pending.request.error
                    if err is None and a.pending.request.result is not None:
                        latency_ms = (now - a.t0) * 1000.0
                        health.on_success(rows, latency_ms)
                        health.record_attempt(latency_ms)
                        if a.hedge:
                            self._hedge_outcome("won")
                        winner = a
                        return a.pending.response()
                    if isinstance(err, ServingTimeoutError):
                        raise err  # deadline contract outranks failover
                    self._record_failure(a.replica, err, tried, state)
                if not attempts:
                    return None
                # 2) Overall deadline (same in-flight grace the engine's
                #    synchronous path has always allowed).
                if deadline is not None and now >= deadline + _DEADLINE_GRACE_S:
                    raise ServingTimeoutError(
                        "request did not complete within its deadline"
                    )
                # 3) Per-attempt budgets: abandon and fail over.
                for a in list(attempts):
                    if a.abandon_at is not None and now >= a.abandon_at:
                        if self._abandon_attempt(a, rows, tried, state):
                            attempts.remove(a)
                if not attempts:
                    return None
                # 4) Hedge: one speculative re-dispatch, once.
                if (hedge_at is not None and now >= hedge_at
                        and len(attempts) == 1):
                    hedge_at = None
                    while alternates:
                        alt = alternates.pop(0)
                        if alt.name in tried or not alt.health.routable():
                            continue
                        hedged = self._dispatch(
                            alt, features, rows, deadline, race, tried,
                            state, hedge=True,
                        )
                        if hedged is not None:
                            attempts.append(hedged)
                            self._hedge_outcome("dispatched")
                            break
                # 5) Sleep to the next edge (or the first terminal event).
                edges = [
                    a.abandon_at for a in attempts if a.abandon_at is not None
                ]
                if deadline is not None:
                    edges.append(deadline + _DEADLINE_GRACE_S)
                if hedge_at is not None:
                    edges.append(hedge_at)
                wait_s = (
                    min(edges) - time.monotonic() if edges
                    else _MAX_WAIT_SLICE_S
                )
                race.wait(min(max(wait_s, 0.0005), _MAX_WAIT_SLICE_S))
                race.clear()
            return None
        finally:
            # No exit path may leave an attempt un-settled: losers (and,
            # on a typed raise, every straggler) are cancelled at the
            # queue and their late results discarded.
            for a in attempts:
                if a is not winner:
                    self._cancel_loser(a, rows)
