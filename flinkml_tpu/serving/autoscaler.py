"""Metric-driven replica autoscaling — the closed control loop over a
:class:`~flinkml_tpu.serving.pool.ReplicaPool` (ROADMAP item 3).

The pool already exports everything an autoscaler needs: per-replica
queued rows and queue capacity (backlog occupancy), the engines' p50/p99
latency gauges, and the health ledgers' outstanding-row balance. This
module closes the loop: a :class:`PoolAutoscaler` samples those signals
every ``interval_s``, smooths backlog into an EWMA (a single saturated
poll must not trigger a replica), and grows/shrinks the pool through
:meth:`ReplicaPool.add_replica` / :meth:`ReplicaPool.remove_replica`.

Design rules, each inherited from an existing subsystem:

- **Hysteresis, the autotune idiom.** A scale event needs a *decisive*
  signal: scale-up fires only when the backlog EWMA exceeds the
  threshold by the same 1.10x margin the tuning table demands before it
  flips a committed default (``decisive_margin``), sustained for
  ``up_consecutive`` evaluations; scale-down needs the mirror-image
  decisively-idle signal for ``down_consecutive`` evaluations plus a
  cooldown. Noise can never flap the replica count for the same reason
  it can never flap a committed knob.
- **Scale-up pays I/O, not XLA compiles.** New replicas warm through the
  PR 11 compile-cache retarget-load path (``share_compiles``): the
  programs the siblings compiled load onto the new placement, and the
  pool seeds the newcomer's latency EWMA from its healthy siblings'
  median so the router sends it load immediately.
- **Leases make colocation negotiable.** A training job that holds
  :func:`~flinkml_tpu.parallel.dispatch.lease_devices` on part of the
  device plane is left alone until serving load demands the slice back:
  with ``reclaim_leases`` the scaler performs the reclaim handshake
  (``request_revoke`` → the trainer releases at its next epoch boundary
  → the freed devices become placements). Skipping the handshake is
  statically detectable — a pool dispatch on a still-leased slice is the
  FML304 shape (:mod:`flinkml_tpu.analysis.collectives`).
- **Replacement outranks hysteresis.** When retirements push the healthy
  count under ``min_replicas`` (the chaos shape: a replica dies
  mid-spike), the scaler replaces it on the next evaluation regardless
  of streaks — the chaos contract extends to the scaling loop.

Metrics (``serving.<pool>.autoscaler``): ``scale_events_total``,
``scale_up_total`` / ``scale_down_total`` / ``replacements_total`` /
``lease_reclaims_total`` counters; ``replicas``, ``backlog_fraction``
(the EWMA), ``observed_p99_ms`` gauges. See
``docs/operators/serving.md`` ("Autoscaling & multi-tenancy").
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Optional

from flinkml_tpu.serving.health import ReplicaState
from flinkml_tpu.serving.pool import ReplicaPool
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("serving.autoscaler")


def _tuned_backlog_threshold(fallback: float = 0.5) -> float:
    """The mesh-keyed ``serving_scale_up_backlog`` autotune knob,
    degraded to the static default on a bad table value (the serving
    knob contract)."""
    from flinkml_tpu.autotune import tuned_default

    try:
        value = float(tuned_default("serving_scale_up_backlog", fallback))
    except (TypeError, ValueError):
        return fallback
    return value if 0.0 < value < 1.0 else fallback


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Control-loop knobs (see module docstring for the policies).

    ``scale_up_backlog=None`` reads the measured threshold for this mesh
    from the autotune table (knob ``serving_scale_up_backlog``; static
    fallback 0.5). Thresholds are fractions of aggregate queue capacity
    (queued rows / sum of ``max_queue_rows``)."""

    min_replicas: int = 1
    max_replicas: int = 8
    scale_up_backlog: Optional[float] = None
    scale_down_backlog: float = 0.05
    #: Optional latency SLO: scale up when the worst replica p99 exceeds
    #: this (decisively), even with queue room left.
    p99_target_ms: Optional[float] = None
    #: The autotune 1.10x decisive-win idiom: signals must beat their
    #: threshold by this factor before an event fires.
    decisive_margin: float = 1.10
    up_consecutive: int = 2
    down_consecutive: int = 8
    cooldown_s: float = 1.0
    interval_s: float = 0.25
    #: EWMA smoothing for the backlog signal (weight of the NEW sample).
    backlog_alpha: float = 0.5
    #: Allow reclaiming training slice leases for scale-up placements
    #: when every unleased device is already carrying a replica.
    reclaim_leases: bool = False
    lease_reclaim_timeout_s: float = 10.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.decisive_margin < 1.0:
            raise ValueError(
                "decisive_margin must be >= 1.0 (it is a hysteresis "
                "band, not a discount)"
            )


class PoolAutoscaler:
    """See module docstring. Drive it with :meth:`start` (background
    control thread) or call :meth:`step` yourself (deterministic tests,
    external schedulers)."""

    def __init__(self, pool: ReplicaPool,
                 config: Optional[AutoscaleConfig] = None):
        self.pool = pool
        self.config = config or AutoscaleConfig()
        self._up_threshold = (
            self.config.scale_up_backlog
            if self.config.scale_up_backlog is not None
            else _tuned_backlog_threshold()
        )
        self._metrics = metrics.group(f"serving.{pool.name}.autoscaler")
        self._backlog_ewma: Optional[float] = None
        self._up_streak = 0
        self._down_streak = 0
        self._last_event = float("-inf")
        self._lock = threading.Lock()  # one step at a time
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- signals -----------------------------------------------------------
    def signals(self) -> Dict[str, Any]:
        """One sample of the pool's scaling signals: instantaneous and
        EWMA backlog fraction, worst healthy-replica p99, counts."""
        replicas = list(self.pool.replicas)
        healthy = [
            r for r in replicas if r.health.state is ReplicaState.HEALTHY
        ]
        queued = 0
        capacity = 0
        worst_p99 = None
        for r in healthy:
            # outstanding_rows (router-submitted, unsettled) is a
            # superset of the batcher's queued rows — counting both
            # would double the signal.
            queued += max(r.health.outstanding_rows, r.engine.queued_rows)
            capacity += r.engine.config.max_queue_rows
            p99 = r.engine.observed_p99_ms
            if p99 is not None:
                worst_p99 = p99 if worst_p99 is None else max(worst_p99, p99)
        backlog = (queued / capacity) if capacity else 0.0
        return {
            "replicas": len(replicas),
            "healthy": len(healthy),
            "backlog_fraction": backlog,
            "worst_p99_ms": worst_p99,
        }

    # -- the control step --------------------------------------------------
    def step(self, now: Optional[float] = None) -> Optional[str]:
        """One control evaluation; returns ``"up"``, ``"down"``,
        ``"replace"``, or None. Thread-safe (the background loop and a
        manual driver may coexist, evaluations serialize)."""
        with self._lock:
            return self._step_locked(
                time.monotonic() if now is None else now
            )

    def _step_locked(self, now: float) -> Optional[str]:
        cfg = self.config
        sig = self.signals()
        alpha = cfg.backlog_alpha
        self._backlog_ewma = (
            sig["backlog_fraction"] if self._backlog_ewma is None
            else (1 - alpha) * self._backlog_ewma
            + alpha * sig["backlog_fraction"]
        )
        self._metrics.gauge("replicas", float(sig["replicas"]))
        self._metrics.gauge("backlog_fraction", self._backlog_ewma)
        if sig["worst_p99_ms"] is not None:
            self._metrics.gauge("observed_p99_ms", sig["worst_p99_ms"])

        # Garbage-collect retirements the pool no longer needs: once the
        # healthy count covers min_replicas, a dead slot is just a
        # leaked stopped engine (a flapping fault would accumulate one
        # per failure). A scaler-managed pool supersedes the manual
        # revive() path — operators who want a dead engine back revive
        # it before the next evaluation.
        if (sig["healthy"] >= cfg.min_replicas
                and sig["replicas"] > sig["healthy"]):
            self.pool.prune_retired()

        # Replacement: a retirement under min_replicas is repaired
        # regardless of streaks (the chaos contract), rate-limited only
        # by the cooldown so a flapping failure cannot fork-bomb.
        if (sig["healthy"] < cfg.min_replicas
                and now - self._last_event >= cfg.cooldown_s):
            if self._grow("replace retired replica"):
                # The replacement supersedes the dead slot.
                self.pool.prune_retired()
                self._metrics.counter("replacements_total")
                self._last_event = now
                return "replace"

        margin = cfg.decisive_margin
        over_backlog = self._backlog_ewma >= self._up_threshold * margin
        over_p99 = (
            cfg.p99_target_ms is not None
            and sig["worst_p99_ms"] is not None
            and sig["worst_p99_ms"] >= cfg.p99_target_ms * margin
        )
        idle_backlog = self._backlog_ewma <= cfg.scale_down_backlog / margin
        p99_fine = (
            cfg.p99_target_ms is None
            or sig["worst_p99_ms"] is None
            or sig["worst_p99_ms"] < cfg.p99_target_ms
        )

        if over_backlog or over_p99:
            self._up_streak += 1
            self._down_streak = 0
        elif idle_backlog and p99_fine:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0

        if now - self._last_event < cfg.cooldown_s:
            return None
        if (self._up_streak >= cfg.up_consecutive
                and sig["healthy"] < cfg.max_replicas):
            reason = (
                f"backlog EWMA {self._backlog_ewma:.2f} >= "
                f"{self._up_threshold:.2f} x {margin}"
                if over_backlog else
                f"p99 {sig['worst_p99_ms']:.1f}ms >= "
                f"{cfg.p99_target_ms}ms x {margin}"
            )
            if self._grow(reason):
                self._metrics.counter("scale_up_total")
                self._metrics.counter("scale_events_total")
                self._last_event = now
                self._up_streak = 0
                return "up"
            return None
        if (self._down_streak >= cfg.down_consecutive
                and sig["healthy"] > cfg.min_replicas
                and len(self.pool.replicas) > cfg.min_replicas):
            try:
                name = self.pool.remove_replica()
            except ValueError:
                return None
            _log.info("autoscaler %s: scale DOWN (%s) — backlog EWMA "
                      "%.3f", self.pool.name, name, self._backlog_ewma)
            self._metrics.counter("scale_down_total")
            self._metrics.counter("scale_events_total")
            self._last_event = now
            self._down_streak = 0
            return "down"
        return None

    # -- placements --------------------------------------------------------
    def _grow(self, reason: str) -> bool:
        """Scale up by one replica, honoring training slice leases: an
        unleased device with the fewest replicas wins; when every
        candidate is leased, either reclaim (``reclaim_leases``: the
        revoke → release handshake) or refuse loudly — NEVER place on a
        still-leased slice (the FML304 shape)."""
        kwargs = self._scale_target()
        universe = self.pool._device_universe
        if universe is None:
            # Mesh-placed pool: no placement universe to draw from.
            _log.warning(
                "autoscaler %s: cannot scale a mesh-placed pool without "
                "an explicit mesh; skipping (%s)", self.pool.name, reason,
            )
            return False
        from flinkml_tpu.parallel import dispatch as _dispatch

        leased = _dispatch.leased_device_ids()
        free = [d for d in universe if d.id not in leased]
        if not free and leased:
            if not self.config.reclaim_leases:
                _log.warning(
                    "autoscaler %s: every candidate device is leased to "
                    "training and reclaim_leases is off; skipping "
                    "scale-up (%s)", self.pool.name, reason,
                )
                return False
            if not self._reclaim_lease(reason):
                return False
            leased = _dispatch.leased_device_ids()
            free = [d for d in universe if d.id not in leased]
            if not free:
                return False
        if not free:
            # Empty universe, or every device leased and reclaim failed:
            # never place on a leased slice (the FML304 shape) and never
            # crash the control loop on min() of nothing.
            _log.warning(
                "autoscaler %s: no unleased placement available; "
                "skipping scale-up (%s)", self.pool.name, reason,
            )
            return False
        per_device: Dict[int, int] = {}
        for r in self.pool.replicas:
            if r.device is not None:
                per_device[r.device.id] = per_device.get(r.device.id, 0) + 1
        device = min(free, key=lambda d: per_device.get(d.id, 0))
        _log.info("autoscaler %s: scale UP onto device %s — %s",
                  self.pool.name, device, reason)
        self.pool.add_replica(device=device, **kwargs)
        return True

    def _scale_target(self) -> Dict[str, Any]:
        """Extra ``add_replica`` kwargs for the neediest target — the
        multi-model pool overrides this decision via ``scale_target()``
        (SLO-weighted); plain pools need nothing."""
        target = getattr(self.pool, "scale_target", None)
        return target() if callable(target) else {}

    def _reclaim_lease(self, reason: str) -> bool:
        """The reclaim handshake: pick the active lease overlapping the
        pool's universe, request revocation, and wait (bounded) for the
        holder to release at its safe boundary."""
        from flinkml_tpu.parallel import dispatch as _dispatch

        universe_ids = {d.id for d in self.pool._device_universe}
        candidates = [
            l for l in _dispatch.active_leases()
            if l.devices & universe_ids
        ]
        if not candidates:
            return False
        # Most-overlapping lease frees the most placement room.
        lease = max(candidates, key=lambda l: len(l.devices & universe_ids))
        _log.warning(
            "autoscaler %s: reclaiming training lease %s (%s)",
            self.pool.name, lease.token, reason,
        )
        lease.request_revoke(f"autoscaler {self.pool.name}: {reason}")
        if not lease.wait_released(self.config.lease_reclaim_timeout_s):
            _log.warning(
                "autoscaler %s: lease %s not released within %.1fs; "
                "will not place on a leased slice",
                self.pool.name, lease.token,
                self.config.lease_reclaim_timeout_s,
            )
            return False
        self._metrics.counter("lease_reclaims_total")
        return True

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "PoolAutoscaler":
        """Start the background control loop (daemon thread, one
        :meth:`step` per ``interval_s``). Returns self."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"autoscaler-{self.pool.name}",
            daemon=True,
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must survive
                _log.exception("autoscaler %s: step failed", self.pool.name)

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
            self._thread = None

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        snap = self._metrics.snapshot()
        return {
            "pool": self.pool.name,
            "replicas": len(self.pool.replicas),
            "backlog_ewma": self._backlog_ewma,
            "up_threshold": self._up_threshold,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }
