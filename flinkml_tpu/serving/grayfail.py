"""Gray-failure defense: quarantine latency outliers, brown out by class.

Every failure the pool could survive before this module was *binary* —
a replica died, erred, or tripped its queue bound. The failure mode
that dominates production serving is the **gray failure**: a replica
that is alive, passing dispatches, and 50–500x slower than its siblings
(a GC-style pause, a contended device, a stuck transfer). One such
replica silently drags pool p99 to its own latency, because nothing
between "healthy" and "dead" exists to catch it.

This module adds that layer, in two halves:

**Router-side containment** (consumed by
:class:`~flinkml_tpu.serving.router.Router`, configured here): the
:class:`GrayFailPolicy` gives every dispatch a per-attempt budget
(healthy-sibling attempt-p99 median × ``deadline_multiplier``, the
multiplier autotune-knobbed as ``serving_deadline_multiplier``) after
which the router ABANDONS the attempt and fails over — and a hedge
threshold after which an idempotent pure-transform request is
speculatively re-dispatched to the next-best replica, first completion
wins, loser cancelled at the queue.

**Pool-side detection** (:class:`GrayFailGuard`): a step-driven watcher
(same shape as the
:class:`~flinkml_tpu.serving.autoscaler.PoolAutoscaler`: ``step()`` for
deterministic tests, ``start()`` for the background thread) that runs a
ROBUST outlier test over the per-replica attempt-latency rings
(:meth:`~flinkml_tpu.serving.health.ReplicaHealth.attempt_p99`):

- a replica whose attempt p99 sits more than ``slow_mad_k`` MADs above
  the healthy-sibling median (MAD = median absolute deviation — robust
  to the outlier itself, unlike a mean/stddev test) for ``slow_trip``
  consecutive evaluations is QUARANTINED: ``HEALTHY -> SLOW``, out of
  routing, *not* killed. The trip/clear thresholds carry the
  autoscaler's decisive-win hysteresis (trip needs the score decisively
  over ``slow_mad_k × decisive_margin``; clear needs it decisively
  under ``slow_mad_k / decisive_margin``) so a replica oscillating at
  the threshold neither flaps in nor flaps out.
- a SLOW replica receives low-rate CANARY dispatches (one tiny request
  every ``canary_interval_s``, bounded by ``canary_timeout_ms``); its
  ring was cleared at quarantine, so the rejoin decision reads only
  post-quarantine evidence. ``slow_clear`` consecutive clean
  evaluations rejoin it (``SLOW -> HEALTHY``) with its EWMA re-seeded
  from the healthy siblings — recovery without operator intervention.
- a quarantine that NEVER recovers escalates: after
  ``quarantine_retire_s`` in SLOW the guard retires the replica
  (``force_unhealthy`` + the pool's retire path), at which point the
  autoscaler's replacement branch takes over. Composition with the
  autoscaler needs no code here: SLOW is not HEALTHY, so a quarantined
  replica already counts against ``min_replicas`` in
  ``PoolAutoscaler.signals()`` and triggers replacement.

**Brownout ladder**: a MAD test cannot see *pool-wide* degradation
(every replica slow — host contention, a shared-device stall): the
median moves with the failure. The guard therefore also tracks the
healthy-median attempt p99 against a slow EWMA baseline of itself;
sustained degradation past ``brownout_multiplier ×`` baseline escalates
a shed LADDER one rung per trip: SLO classes are refused admission in
``shed_order`` (batch first), via the existing typed
:class:`~flinkml_tpu.serving.errors.SLOAdmissionError`, so the
interactive tier keeps its latency while the batch tier backs off —
instead of every class timing out equally. Recovery de-escalates one
rung at a time.

Metrics (``serving.<pool>.grayfail``): ``quarantines_total``,
``rejoins_total``, ``slow_retired_total``, ``canary_probes`` /
``canary_errors``, ``brownout_escalations`` / ``brownout_deescalations``
counters; ``brownout_level`` gauge. Per-replica ``slow_score`` gauges
publish into the pool's labeled engine group (``serving.<pool>``,
``replica=<name>``). The router adds ``serving.<pool>.hedges``
(labeled ``outcome=dispatched|won|lost``) and its own
``abandoned_attempts`` counter.
"""

from __future__ import annotations

import dataclasses
import statistics
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.serving.engine import _tuned_float
from flinkml_tpu.serving.health import ReplicaState
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("serving.grayfail")


class ReplicaQuarantinedError(RuntimeError):
    """Administrative error recorded when the guard retires a replica
    whose quarantine never recovered (``quarantine_retire_s``)."""


@dataclasses.dataclass(frozen=True)
class GrayFailPolicy:
    """Knobs for the whole gray-failure stack (module docstring).

    The floors (``attempt_floor_ms``, ``hedge_floor_ms``,
    ``slow_abs_floor_ms``, ``brownout_abs_floor_ms``) keep the defenses
    quiet on fast pools: a CPU-mesh pool serving in single-digit
    milliseconds must not abandon, hedge, or quarantine over
    scheduler-timeslice noise that a multiplier alone would amplify.
    Production-true latencies clear the floors by construction; tests
    lower them explicitly."""

    # -- per-dispatch deadlines (router-side abandonment)
    abandon: bool = True
    #: Budget = healthy-sibling attempt-p99 median × this. None reads
    #: the autotune table knob ``serving_deadline_multiplier``
    #: (fallback 4.0) — the tuned_default contract: a bad table value
    #: degrades to the static default.
    deadline_multiplier: Optional[float] = None
    attempt_floor_ms: float = 250.0
    #: Sibling rings need this many attempts before their p99 is
    #: trusted for budgets/hedging — no abandonment on cold pools.
    min_attempt_samples: int = 20
    # -- hedged requests (router-side)
    hedge: bool = True
    hedge_multiplier: float = 1.5
    hedge_floor_ms: float = 100.0
    # -- latency-outlier quarantine (guard-side)
    slow_mad_k: float = 6.0
    slow_abs_floor_ms: float = 20.0
    slow_trip: int = 3
    slow_clear: int = 3
    #: The autoscaler's decisive-win margin, applied to the MAD score:
    #: trip only when score > k × margin, clear only when score < k / margin.
    decisive_margin: float = 1.10
    min_slow_samples: int = 20
    canary_interval_s: float = 0.5
    canary_timeout_ms: float = 1000.0
    canary_min_samples: int = 3
    #: SLOW longer than this -> retire (autoscaler replaces). None: never.
    quarantine_retire_s: Optional[float] = 120.0
    #: Refuse a quarantine that would leave fewer HEALTHY replicas.
    min_healthy_after_quarantine: int = 1
    # -- brownout ladder (guard-side)
    brownout: bool = True
    #: SLO classes shed under pool-wide degradation, in order: one rung
    #: of the ladder per sustained trip, batch first by default.
    shed_order: Tuple[str, ...] = ("batch",)
    brownout_multiplier: float = 3.0
    brownout_abs_floor_ms: float = 50.0
    brownout_trip: int = 4
    brownout_clear: int = 4
    baseline_alpha: float = 0.1

    def resolved_deadline_multiplier(self) -> float:
        if self.deadline_multiplier is not None:
            return float(self.deadline_multiplier)
        return _tuned_float("serving_deadline_multiplier", 4.0)


class GrayFailGuard:
    """Pool-side gray-failure watcher — see the module docstring.

    ``step()`` is the whole brain (deterministic tests drive it
    directly); ``start()`` runs it on a daemon thread every
    ``interval_s``, exactly the autoscaler's shape."""

    def __init__(self, pool: Any, policy: Optional[GrayFailPolicy] = None,
                 interval_s: float = 0.25):
        self.pool = pool
        self.policy = policy or getattr(pool, "grayfail_policy", None) \
            or GrayFailPolicy()
        self.interval_s = float(interval_s)
        self._metrics = metrics.group(f"serving.{pool.name}.grayfail")
        self._slow_streak: Dict[str, int] = {}
        self._clear_streak: Dict[str, int] = {}
        self._last_canary: Dict[str, float] = {}
        self._brownout_level = 0
        self._brownout_streak = 0
        self._brownout_clear_streak = 0
        self._baseline_p99: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._canary_columns: Optional[Dict[str, np.ndarray]] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GrayFailGuard":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — guard must outlive one bad step
                    _log.exception("gray-failure guard step failed")

        self._thread = threading.Thread(
            target=_loop, name=f"grayfail-{self.pool.name}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def brownout_level(self) -> int:
        return self._brownout_level

    # -- the evaluation step ----------------------------------------------
    def step(self, now: Optional[float] = None) -> List[str]:
        """One evaluation pass. Returns the actions taken (for logs and
        deterministic tests): ``quarantine:<r>``, ``rejoin:<r>``,
        ``retire:<r>``, ``brownout:<level>``."""
        now = time.monotonic() if now is None else now
        pol = self.policy
        actions: List[str] = []
        replicas = list(self.pool.replicas)
        healthy = [
            r for r in replicas if r.health.state is ReplicaState.HEALTHY
        ]
        p99s = {
            r.name: r.health.attempt_p99(min_samples=pol.min_slow_samples)
            for r in healthy
        }
        known = [v for v in p99s.values() if v is not None]
        med = statistics.median(known) if known else None
        mad = None
        if med is not None and len(known) >= 2:
            mad = statistics.median([abs(v - med) for v in known])
            # MAD floor: quantized/identical latencies give MAD 0, which
            # would make any epsilon an infinite score.
            mad = max(mad, 0.05 * med, 0.1)
        self._prune_gone({r.name for r in replicas})
        if mad is not None:
            actions += self._check_outliers(healthy, p99s, med, mad)
        actions += self._run_quarantine(replicas, p99s, med, mad, now)
        if pol.brownout:
            actions += self._check_brownout(med)
        return actions

    def _prune_gone(self, live: set) -> None:
        for d in (self._slow_streak, self._clear_streak, self._last_canary):
            for name in list(d):
                if name not in live:
                    del d[name]

    # -- quarantine entry --------------------------------------------------
    def _score(self, p99: float, med: float, mad: float) -> float:
        return (p99 - med) / mad

    def _check_outliers(self, healthy, p99s, med, mad) -> List[str]:
        pol = self.policy
        actions: List[str] = []
        for r in healthy:
            p99 = p99s.get(r.name)
            if p99 is None:
                continue
            score = self._score(p99, med, mad)
            metrics.group(
                f"serving.{self.pool.name}", labels={"replica": r.name}
            ).gauge("slow_score", round(score, 3))
            tripping = (
                score > pol.slow_mad_k * pol.decisive_margin
                and (p99 - med) > pol.slow_abs_floor_ms
            )
            if not tripping:
                self._slow_streak[r.name] = 0
                continue
            self._slow_streak[r.name] = self._slow_streak.get(r.name, 0) + 1
            if self._slow_streak[r.name] < pol.slow_trip:
                continue
            remaining = sum(
                1 for h in healthy
                if h is not r and h.health.state is ReplicaState.HEALTHY
            )
            if remaining < pol.min_healthy_after_quarantine:
                _log.warning(
                    "pool %s: replica %s is a latency outlier (score %.1f) "
                    "but quarantine would leave %d healthy — refusing",
                    self.pool.name, r.name, score, remaining,
                )
                continue
            if r.health.mark_slow():
                self._slow_streak[r.name] = 0
                self._clear_streak[r.name] = 0
                self._metrics.counter("quarantines_total")
                self.pool._update_health_gauge()
                _log.warning(
                    "pool %s: QUARANTINED replica %s — attempt p99 %.1fms "
                    "vs healthy median %.1fms (MAD score %.1f > %g); "
                    "canary probes every %.2fs",
                    self.pool.name, r.name, p99, med, score,
                    pol.slow_mad_k, pol.canary_interval_s,
                )
                actions.append(f"quarantine:{r.name}")
        return actions

    # -- canary probing + rejoin/retire -------------------------------------
    def _canary_features(self) -> Optional[Dict[str, np.ndarray]]:
        if self._canary_columns is None:
            example = getattr(self.pool, "_example", None)
            if example is None:
                return None
            self._canary_columns = {
                c: np.asarray(example.column(c))[:1]
                for c in example.column_names
            }
        return self._canary_columns

    def _probe(self, replica) -> None:
        """One canary dispatch against a SLOW replica; the observation
        (success latency or censored timeout) lands in the replica's
        attempt ring, which is all the rejoin decision reads."""
        pol = self.policy
        features = self._canary_features()
        if features is None:
            return
        self._metrics.counter("canary_probes")
        t0 = time.monotonic()
        try:
            pending = replica.engine.submit(
                features, timeout_ms=pol.canary_timeout_ms
            )
        except BaseException as e:  # noqa: BLE001 — probe failure is data
            self._metrics.counter("canary_errors")
            if replica.health.on_error(e):
                self.pool._retire(replica, e)
            return
        if pending.wait(pol.canary_timeout_ms / 1000.0):
            try:
                pending.response()
            except BaseException as e:  # noqa: BLE001 — probe failure is data
                self._metrics.counter("canary_errors")
                if replica.health.on_error(e):
                    self.pool._retire(replica, e)
                return
            replica.health.record_attempt((time.monotonic() - t0) * 1000.0)
        else:
            pending.abandon()
            replica.health.record_attempt(
                pol.canary_timeout_ms, abandoned=True
            )

    def _run_quarantine(self, replicas, p99s, med, mad, now) -> List[str]:
        pol = self.policy
        actions: List[str] = []
        for r in replicas:
            if r.health.state is not ReplicaState.SLOW:
                continue
            if pol.quarantine_retire_s is not None and (
                r.health.state_age_s() > pol.quarantine_retire_s
            ):
                err = ReplicaQuarantinedError(
                    f"replica {r.name} stayed SLOW past "
                    f"{pol.quarantine_retire_s}s without recovering"
                )
                if r.health.force_unhealthy(err):
                    self._metrics.counter("slow_retired_total")
                    self.pool._retire(r, err)
                    actions.append(f"retire:{r.name}")
                continue
            last = self._last_canary.get(r.name, 0.0)
            if now - last >= pol.canary_interval_s:
                self._last_canary[r.name] = now
                self._probe(r)
            # Recovery is judged on the NEWEST canary window only: a
            # replica that just recovered must not stay quarantined
            # (and eventually be retired) because its stall-era canary
            # observations are still in the ring.
            canary_p99 = r.health.recent_attempt_p99(
                pol.canary_min_samples, min_samples=pol.canary_min_samples
            )
            recovered = False
            if canary_p99 is not None and med is not None and mad is not None:
                score = self._score(canary_p99, med, mad)
                recovered = (
                    score < pol.slow_mad_k / pol.decisive_margin
                    or (canary_p99 - med) <= pol.slow_abs_floor_ms
                )
            if recovered:
                streak = self._clear_streak.get(r.name, 0) + 1
                self._clear_streak[r.name] = streak
                if streak >= pol.slow_clear and r.health.clear_slow():
                    self._clear_streak[r.name] = 0
                    self._metrics.counter("rejoins_total")
                    self.pool._seed_ewma(r)
                    self.pool._update_health_gauge()
                    _log.info(
                        "pool %s: replica %s REJOINED after quarantine "
                        "(canary p99 %.1fms vs healthy median %.1fms)",
                        self.pool.name, r.name, canary_p99, med,
                    )
                    actions.append(f"rejoin:{r.name}")
            else:
                self._clear_streak[r.name] = 0
        return actions

    # -- brownout ladder -----------------------------------------------------
    def _check_brownout(self, pool_p99: Optional[float]) -> List[str]:
        pol = self.policy
        actions: List[str] = []
        if pool_p99 is None:
            return actions
        degraded = False
        if self._baseline_p99 is not None:
            threshold = max(
                self._baseline_p99 * pol.brownout_multiplier,
                self._baseline_p99 + pol.brownout_abs_floor_ms,
            )
            degraded = pool_p99 > threshold
        if not degraded:
            # Only a non-degraded sample may move the baseline: letting
            # the baseline chase a brownout would define the failure away.
            a = pol.baseline_alpha
            self._baseline_p99 = (
                pool_p99 if self._baseline_p99 is None
                else (1 - a) * self._baseline_p99 + a * pool_p99
            )
        if degraded:
            self._brownout_clear_streak = 0
            self._brownout_streak += 1
            if (
                self._brownout_streak >= pol.brownout_trip
                and self._brownout_level < len(pol.shed_order)
            ):
                self._brownout_streak = 0
                self._brownout_level += 1
                self._metrics.counter("brownout_escalations")
                actions.append(f"brownout:{self._brownout_level}")
                _log.warning(
                    "pool %s: BROWNOUT level %d — shedding SLO classes %s "
                    "(pool p99 %.1fms vs baseline %.1fms)",
                    self.pool.name, self._brownout_level,
                    pol.shed_order[:self._brownout_level],
                    pool_p99, self._baseline_p99 or float("nan"),
                )
        else:
            self._brownout_streak = 0
            if self._brownout_level > 0:
                self._brownout_clear_streak += 1
                if self._brownout_clear_streak >= pol.brownout_clear:
                    self._brownout_clear_streak = 0
                    self._brownout_level -= 1
                    self._metrics.counter("brownout_deescalations")
                    actions.append(f"brownout:{self._brownout_level}")
                    _log.info(
                        "pool %s: brownout de-escalated to level %d",
                        self.pool.name, self._brownout_level,
                    )
        self._metrics.gauge("brownout_level", float(self._brownout_level))
        shed = frozenset(pol.shed_order[:self._brownout_level])
        if shed != self.pool.brownout_shed_classes:
            self.pool.set_brownout(shed)
        return actions
