"""Micro-batching: coalesce concurrent requests into shape buckets.

The fused pipeline executor (:mod:`flinkml_tpu.pipeline_fusion`) compiles
one program per power-of-two row bucket and serves any row count within a
bucket with zero retraces — so the *only* cost of batching requests
together is padding waste inside the bucket, and the only cost of not
batching is per-dispatch overhead. Two policies share that structure:

:class:`AdaptiveMicroBatcher` (the PR 3 policy, in the adaptive-batching
tradition of Clipper, Crankshaw et al., NSDI'17) packs whole requests
FIFO:

  - a request that arrives alone waits at most ``max_wait_s`` for company
    (the latency the operator is willing to trade for occupancy);
  - the window closes EARLY the moment the queued rows exactly fill their
    power-of-two bucket (occupancy 1.0 — waiting longer buys nothing the
    compile cache doesn't already give a later batch) or reach
    ``max_batch_rows``;
  - requests are never split, so a request too large for the batch's
    remaining capacity blocks everything behind it (head-of-line).

:class:`ContinuousBatcher` (the Orca-style policy, Yu et al., OSDI'22,
specialized to bucketed row batching) splits requests at bucket
boundaries instead:

  - a late arrival joins the **currently forming bucket**: when queued
    rows reach the bucket the window opened on, the window closes and
    exactly that bucket dispatches (occupancy 1.0), the straddling
    request contributing only its head rows;
  - the tail rows stay at the FRONT of the queue and ride the next
    dispatch — no request ever waits behind a batch it could have
    partially joined, which is what deletes the FIFO policy's
    head-of-line latency under load;
  - per-request row reassembly lives in :class:`ServingRequest`
    (:meth:`ServingRequest.add_segment`): responses are stitched back in
    row order, and a request whose segments were served by different
    model versions is re-dispatched whole so the version-tagging
    contract (one response == one version, bitwise-equal to that
    version's transform) survives splitting.

Both policies share bounded admission: past ``max_queue_rows`` queued
rows, :meth:`offer` refuses and the engine sheds or rejects — queueing
theory does the rest of the argument (an unbounded queue under
saturation has unbounded latency). Deadlines are swept **promptly**: the
consumer wakes at the earliest queued deadline and fails overdue
requests the moment it passes, instead of letting them ride out the
max-wait window (a never-filling queue used to hold an expired request
for the whole window).

Thread-safe; one consumer (the engine's dispatcher thread) and any
number of producers.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.pipeline_fusion import row_bucket
from flinkml_tpu.serving.errors import EngineStoppedError


@dataclasses.dataclass(eq=False)  # identity equality: queues remove by
class ServingRequest:             # object, and columns hold numpy arrays
    """One in-flight ``predict`` call: host input columns plus a
    completion event the calling thread waits on. Under continuous
    batching a request may be served in several row SEGMENTS; the
    dispatcher feeds them to :meth:`add_segment` and the request
    reassembles its response in row order."""

    columns: Dict[str, np.ndarray]
    rows: int
    enqueued_at: float
    deadline: Optional[float] = None  # absolute, time.monotonic() clock
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: Optional[Dict[str, np.ndarray]] = None
    error: Optional[BaseException] = None
    version: Optional[int] = None
    shed: bool = False
    #: Rows the batcher has handed out in segments (queue-side cursor;
    #: only the consumer thread advances it, under the batcher's lock).
    dispatched_rows: int = 0
    #: Completed ``(start, columns, version, rows)`` segments awaiting
    #: reassembly. Only the dispatcher thread touches this.
    segments: List[Tuple[int, Dict[str, np.ndarray], Optional[int], int]] = (
        dataclasses.field(default_factory=list)
    )
    #: Set by whichever side (client wait-expiry or dispatcher in-queue
    #: expiry) counts the timeout first, so one request never increments
    #: the 'timeouts' counter twice. Guarded by ``_count_lock`` — use
    #: :meth:`claim_timeout_count`.
    timeout_counted: bool = False
    #: True once the submitter stopped waiting on this request
    #: (per-attempt deadline or a hedge race loss): any later batch
    #: result is DISCARDED — the gray-failure abandonment contract. Set
    #: only via :meth:`abandon`, under ``_count_lock``.
    abandoned: bool = False
    #: Optional shared event a router racing several attempts of one
    #: logical request waits on; set on EVERY terminal transition
    #: (complete/fail/abandon) so the racer wakes on the first edge.
    race: Optional[threading.Event] = None
    _count_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock
    )

    def claim_timeout_count(self) -> bool:
        """Atomic test-and-set: True for exactly one caller (the client's
        wait-expiry and the dispatcher's in-queue expiry can race)."""
        with self._count_lock:
            if self.timeout_counted:
                return False
            self.timeout_counted = True
            return True

    def _terminal(self) -> None:
        """Caller holds ``_count_lock`` and just decided the outcome."""
        self.done.set()
        if self.race is not None:
            self.race.set()

    def complete(self, result: Dict[str, np.ndarray],
                 version: Optional[int], shed: bool = False) -> bool:
        """First terminal transition wins (CAS): False when the request
        already completed, failed, or was ABANDONED — the caller discards
        the straggler result instead of publishing a duplicate or
        mis-versioned response."""
        with self._count_lock:
            if self.done.is_set():
                return False
            self.result = result
            self.version = version
            self.shed = shed
            self._terminal()
            return True

    def fail(self, error: BaseException) -> bool:
        with self._count_lock:
            if self.done.is_set():
                return False
            self.error = error
            self._terminal()
            return True

    def abandon(self) -> bool:
        """Stop waiting on this request (per-attempt deadline expiry or a
        lost hedge race). CAS: True for exactly one abandoner, False when
        a result/error already landed. After abandonment the request's
        queued tail rows are released at the batcher's next sweep and any
        in-flight straggler result is discarded by :meth:`complete`'s
        CAS — a late straggler can never produce a duplicate response."""
        with self._count_lock:
            if self.done.is_set():
                return False
            self.abandoned = True
            self._terminal()
            return True

    # -- segment reassembly (dispatcher thread only) -----------------------
    def add_segment(self, start: int, columns: Dict[str, np.ndarray],
                    version: Optional[int], rows: int):
        """Record one served segment. Returns ``None`` while more rows
        are outstanding, the assembled ``(columns, version)`` response
        when all rows landed on one version (the caller completes the
        request), the string ``"mixed"`` when segments span model
        versions — the caller must :meth:`reset_segments` and
        re-dispatch the whole request so the response stays
        single-version — or the string ``"discarded"`` when the request
        reached a terminal state (abandoned, expired, failed) while the
        segment was in flight: the straggler rows are dropped here and
        the caller counts the discard."""
        if self.done.is_set():  # abandoned/expired/failed mid-flight
            return "discarded"
        self.segments.append((start, columns, version, rows))
        served = sum(r for _, _, _, r in self.segments)
        if served < self.rows:
            return None
        versions = {v for _, _, v, _ in self.segments}
        if len(versions) > 1:
            return "mixed"
        self.segments.sort(key=lambda s: s[0])
        if len(self.segments) == 1:
            assembled = self.segments[0][1]
        else:
            names = self.segments[0][1].keys()
            assembled = {
                c: np.concatenate([cols[c] for _, cols, _, _ in self.segments])
                for c in names
            }
        return assembled, versions.pop()

    def reset_segments(self) -> None:
        """Discard partial results ahead of a whole-request re-dispatch
        (version skew across a hot swap)."""
        self.segments.clear()


@dataclasses.dataclass(frozen=True)
class BatchSegment:
    """One contiguous row range of a request inside a dispatched batch.
    Whole-request policies emit one full-range segment per request."""

    request: ServingRequest
    start: int
    rows: int

    @property
    def columns(self) -> Dict[str, np.ndarray]:
        if self.start == 0 and self.rows == self.request.rows:
            return self.request.columns
        return {
            name: a[self.start:self.start + self.rows]
            for name, a in self.request.columns.items()
        }


class AdaptiveMicroBatcher:
    """Bounded thread-safe request queue + FIFO whole-request packing."""

    def __init__(
        self,
        max_batch_rows: int = 1024,
        max_wait_s: float = 0.002,
        max_queue_rows: int = 8192,
    ):
        if max_batch_rows < 1:
            raise ValueError(f"max_batch_rows must be >= 1, got {max_batch_rows}")
        if max_queue_rows < max_batch_rows:
            raise ValueError(
                f"max_queue_rows ({max_queue_rows}) must be >= "
                f"max_batch_rows ({max_batch_rows})"
            )
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_s)
        self.max_queue_rows = int(max_queue_rows)
        self._cond = threading.Condition()
        self._queue: Deque[ServingRequest] = collections.deque()
        self._queued_rows = 0
        self._stopped = False

    # -- producer side -----------------------------------------------------
    def offer(self, request: ServingRequest) -> bool:
        """Admit ``request``; False when the bounded queue is full (the
        engine decides between shedding and a typed rejection). Raises
        :class:`EngineStoppedError` after :meth:`stop`."""
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("serving engine is stopped")
            if self._queued_rows + request.rows > self.max_queue_rows:
                return False
            self._queue.append(request)
            self._queued_rows += request.rows
            self._cond.notify_all()
            return True

    def requeue(self, request: ServingRequest) -> bool:
        """Put a request back at the FRONT of the queue for a whole
        re-dispatch (mixed-version reassembly across a hot swap). False
        after :meth:`stop` — the caller fails the request instead."""
        with self._cond:
            if self._stopped:
                return False
            request.dispatched_rows = 0
            request.reset_segments()
            self._queue.appendleft(request)
            self._queued_rows += request.rows
            self._cond.notify_all()
            return True

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    # -- consumer side (the dispatcher thread) -----------------------------
    def next_batch(
        self, poll_s: float = 0.05
    ) -> Tuple[List[BatchSegment], List[ServingRequest]]:
        """Block up to ``poll_s`` for work, then apply the batching window;
        returns ``(batch, expired)`` — either may be empty. ``expired``
        are requests whose deadline passed while queued (the caller fails
        them with the timeout error); they never occupy batch rows, and
        an expiry observed mid-window returns IMMEDIATELY so the typed
        timeout is prompt rather than delayed to the window's close."""
        with self._cond:
            if not self._queue and not self._stopped:
                self._cond.wait(poll_s)
            expired = self._drop_expired()
            if not self._queue:
                return [], expired
            # Batching window, anchored to the OLDEST queued request — but
            # never waiting past any queued request's deadline: a request
            # whose deadline falls inside the window closes it early (less
            # a small margin) so it dispatches in time instead of being
            # expired by the very wait that was supposed to batch it.
            window_end = self._queue[0].enqueued_at + self.max_wait_s
            forming_bucket = min(
                self.max_batch_rows, row_bucket(self._queued_rows)
            )
            while not self._stopped:
                newly_expired = self._drop_expired()
                if newly_expired:
                    # Prompt sweep: fail overdue requests NOW (the caller
                    # raises the typed timeout) instead of holding them —
                    # or the window — until the max-wait elapses.
                    expired.extend(newly_expired)
                    return [], expired
                if not self._queue:
                    return [], expired
                rows = self._queued_rows
                if rows >= self.max_batch_rows:
                    break
                if self._close_early(rows, forming_bucket):
                    break
                deadlines = [
                    r.deadline for r in self._queue if r.deadline is not None
                ]
                close_at = window_end
                if deadlines:
                    close_at = min(close_at, min(deadlines) - 0.005)
                remaining = close_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return self._pop_batch(forming_bucket), expired

    def _close_early(self, rows: int, forming_bucket: int) -> bool:
        # Bucket exactly full: occupancy 1.0, waiting buys nothing.
        return rows == row_bucket(rows)

    def _discard_if_dead(self, req: ServingRequest) -> bool:
        """Drop a queued request that already completed or failed (a
        split request's earlier batch erred, or shutdown failed it) —
        its remaining rows must neither occupy batch rows nor inflate
        the admission bound. Caller holds the lock and ``req`` is the
        queue head."""
        if not req.done.is_set():
            return False
        self._queue.popleft()
        self._queued_rows -= req.rows - req.dispatched_rows
        return True

    def _pop_batch(self, forming_bucket: int) -> List[BatchSegment]:
        """FIFO whole-request packing (never splits)."""
        batch: List[BatchSegment] = []
        rows = 0
        while self._queue:
            req = self._queue[0]
            if self._discard_if_dead(req):
                continue
            if batch and rows + req.rows > self.max_batch_rows:
                break
            self._queue.popleft()
            self._queued_rows -= req.rows
            batch.append(BatchSegment(req, 0, req.rows))
            rows += req.rows
            if rows >= self.max_batch_rows:
                break
        return batch

    def _drop_expired(self) -> List[ServingRequest]:
        now = time.monotonic()
        expired, dead = [], []
        for r in self._queue:
            if r.done.is_set():
                # Abandoned (or failed elsewhere) while queued: cancel at
                # the queue — its remaining rows stop occupying admission
                # capacity NOW, not when it reaches the head. This is the
                # hedge-loser cancellation path.
                dead.append(r)
            elif r.deadline is not None and r.deadline <= now:
                expired.append(r)
        for r in dead:
            self._queue.remove(r)
            self._queued_rows -= r.rows - r.dispatched_rows
        for r in expired:
            self._queue.remove(r)
            self._queued_rows -= r.rows - r.dispatched_rows
        return expired

    # -- shutdown ----------------------------------------------------------
    def stop(self) -> None:
        """Refuse new offers; the consumer may keep draining."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def drain_pending(self) -> List[ServingRequest]:
        """Pop every queued request (shutdown without drain: the engine
        fails them with :class:`EngineStoppedError`)."""
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            return pending


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    return 1 << (int(n).bit_length() - 1)


class ContinuousBatcher(AdaptiveMicroBatcher):
    """Continuous batching: requests split at bucket boundaries (see the
    module docstring). Shares admission, deadlines, and shutdown with the
    FIFO policy; only the window-close condition and the pop differ."""

    def _close_early(self, rows: int, forming_bucket: int) -> bool:
        # Late arrivals filled the bucket the window opened on: dispatch
        # exactly that full bucket now (the straddler splits), instead of
        # waiting out the window only to pad a larger bucket.
        return rows >= forming_bucket or rows == row_bucket(rows)

    def _pop_batch(self, forming_bucket: int) -> List[BatchSegment]:
        q = self._queued_rows
        if q >= self.max_batch_rows:
            # Saturated: every dispatch is an exactly-full cap bucket.
            target = self.max_batch_rows
        elif q >= forming_bucket:
            # The forming bucket filled (early close): take the largest
            # exactly-full bucket available — zero padding; the remainder
            # opens the next window at the queue front.
            target = min(self.max_batch_rows, _pow2_floor(q))
        else:
            # Window expired under-full: latency beats occupancy, flush
            # everything (padded to its bucket by the executor).
            target = q
        batch: List[BatchSegment] = []
        taken = 0
        while self._queue and taken < target:
            req = self._queue[0]
            if self._discard_if_dead(req):
                # A failed head batch killed this request; its tail rows
                # must not be dispatched as dead device work.
                continue
            remaining = req.rows - req.dispatched_rows
            take = min(remaining, target - taken)
            batch.append(BatchSegment(req, req.dispatched_rows, take))
            req.dispatched_rows += take
            self._queued_rows -= take
            taken += take
            if req.dispatched_rows >= req.rows:
                self._queue.popleft()
        return batch
