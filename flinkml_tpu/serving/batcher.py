"""Adaptive micro-batching: coalesce concurrent requests into shape buckets.

The fused pipeline executor (:mod:`flinkml_tpu.pipeline_fusion`) compiles
one program per power-of-two row bucket and serves any row count within a
bucket with zero retraces — so the *only* cost of batching requests
together is padding waste inside the bucket, and the only cost of not
batching is per-dispatch overhead. The policy here (in the adaptive-
batching tradition of Clipper, Crankshaw et al., NSDI'17) exploits that
structure directly:

  - a request that arrives alone waits at most ``max_wait_s`` for company
    (the latency the operator is willing to trade for occupancy);
  - the window closes EARLY the moment the queued rows exactly fill their
    power-of-two bucket (occupancy 1.0 — waiting longer buys nothing the
    compile cache doesn't already give a later batch) or reach
    ``max_batch_rows``;
  - admission is bounded: past ``max_queue_rows`` queued rows,
    :meth:`offer` refuses and the engine sheds or rejects — queueing
    theory does the rest of the argument (an unbounded queue under
    saturation has unbounded latency).

Requests are never split across batches; batches pop FIFO, so the oldest
request's deadline governs the window. Thread-safe; one consumer (the
engine's dispatcher thread) and any number of producers.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.pipeline_fusion import row_bucket
from flinkml_tpu.serving.errors import EngineStoppedError


@dataclasses.dataclass
class ServingRequest:
    """One in-flight ``predict`` call: host input columns plus a
    completion event the calling thread waits on."""

    columns: Dict[str, np.ndarray]
    rows: int
    enqueued_at: float
    deadline: Optional[float] = None  # absolute, time.monotonic() clock
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: Optional[Dict[str, np.ndarray]] = None
    error: Optional[BaseException] = None
    version: Optional[int] = None
    shed: bool = False
    #: Set by whichever side (client wait-expiry or dispatcher in-queue
    #: expiry) counts the timeout first, so one request never increments
    #: the 'timeouts' counter twice. Guarded by ``_count_lock`` — use
    #: :meth:`claim_timeout_count`.
    timeout_counted: bool = False
    _count_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock
    )

    def claim_timeout_count(self) -> bool:
        """Atomic test-and-set: True for exactly one caller (the client's
        wait-expiry and the dispatcher's in-queue expiry can race)."""
        with self._count_lock:
            if self.timeout_counted:
                return False
            self.timeout_counted = True
            return True

    def complete(self, result: Dict[str, np.ndarray],
                 version: Optional[int], shed: bool = False) -> None:
        self.result = result
        self.version = version
        self.shed = shed
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()


class AdaptiveMicroBatcher:
    """Bounded thread-safe request queue + the coalescing policy above."""

    def __init__(
        self,
        max_batch_rows: int = 1024,
        max_wait_s: float = 0.002,
        max_queue_rows: int = 8192,
    ):
        if max_batch_rows < 1:
            raise ValueError(f"max_batch_rows must be >= 1, got {max_batch_rows}")
        if max_queue_rows < max_batch_rows:
            raise ValueError(
                f"max_queue_rows ({max_queue_rows}) must be >= "
                f"max_batch_rows ({max_batch_rows})"
            )
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_s)
        self.max_queue_rows = int(max_queue_rows)
        self._cond = threading.Condition()
        self._queue: Deque[ServingRequest] = collections.deque()
        self._queued_rows = 0
        self._stopped = False

    # -- producer side -----------------------------------------------------
    def offer(self, request: ServingRequest) -> bool:
        """Admit ``request``; False when the bounded queue is full (the
        engine decides between shedding and a typed rejection). Raises
        :class:`EngineStoppedError` after :meth:`stop`."""
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("serving engine is stopped")
            if self._queued_rows + request.rows > self.max_queue_rows:
                return False
            self._queue.append(request)
            self._queued_rows += request.rows
            self._cond.notify_all()
            return True

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    # -- consumer side (the dispatcher thread) -----------------------------
    def next_batch(
        self, poll_s: float = 0.05
    ) -> Tuple[List[ServingRequest], List[ServingRequest]]:
        """Block up to ``poll_s`` for work, then apply the batching window;
        returns ``(batch, expired)`` — either may be empty. ``expired``
        are requests whose deadline passed while queued (the caller fails
        them with the timeout error); they never occupy batch rows."""
        with self._cond:
            if not self._queue and not self._stopped:
                self._cond.wait(poll_s)
            expired = self._drop_expired()
            if not self._queue:
                return [], expired
            # Batching window, anchored to the OLDEST queued request — but
            # never waiting past any queued request's deadline: a request
            # whose deadline falls inside the window closes it early (less
            # a small margin) so it dispatches in time instead of being
            # expired by the very wait that was supposed to batch it.
            window_end = self._queue[0].enqueued_at + self.max_wait_s
            while not self._stopped:
                rows = self._queued_rows
                if rows >= self.max_batch_rows:
                    break
                if rows == row_bucket(rows):
                    break  # bucket exactly full: occupancy 1.0, go now
                deadlines = [
                    r.deadline for r in self._queue if r.deadline is not None
                ]
                close_at = window_end
                if deadlines:
                    close_at = min(close_at, min(deadlines) - 0.005)
                remaining = close_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            # No re-expiry after the window: a deadline that lapsed DURING
            # the window (bounded by max_wait_s) rides the batch — the
            # caller's completion wait carries a grace margin, and
            # dispatching beats wasting the rows. Requests overdue before
            # the window (queued behind a busy dispatcher) were dropped
            # above.
            batch: List[ServingRequest] = []
            rows = 0
            while self._queue:
                req = self._queue[0]
                if batch and rows + req.rows > self.max_batch_rows:
                    break
                self._queue.popleft()
                self._queued_rows -= req.rows
                batch.append(req)
                rows += req.rows
                if rows >= self.max_batch_rows:
                    break
            return batch, expired

    def _drop_expired(self) -> List[ServingRequest]:
        now = time.monotonic()
        expired = [
            r for r in self._queue if r.deadline is not None and r.deadline <= now
        ]
        for r in expired:
            self._queue.remove(r)
            self._queued_rows -= r.rows
        return expired

    # -- shutdown ----------------------------------------------------------
    def stop(self) -> None:
        """Refuse new offers; the consumer may keep draining."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def drain_pending(self) -> List[ServingRequest]:
        """Pop every queued request (shutdown without drain: the engine
        fails them with :class:`EngineStoppedError`)."""
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            return pending
