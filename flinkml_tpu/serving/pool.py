"""Replica pool: N serving engines behind one router — serving scale-out.

The reference serves models through Flink's parallel task slots; here
the slot is a :class:`~flinkml_tpu.serving.engine.ServingEngine` replica
and the parallelism substrate is the device plane (ROADMAP item 3). A
:class:`ReplicaPool` spins up one engine per **device** (the fused
executor's single-device programs dispatch lock-free and in parallel —
each replica's dispatcher thread owns one device via
``jax.default_device``) or per **mesh slice** (SPMD models: each replica
holds ``local_execution_lock(slice)`` per batch, so pools time-share
devices with concurrent training exactly like concurrent fits do, and
the slice locks compose through ``parallel.dispatch``'s overlap
machinery — analyzer-checkable, FML303).

What the pool adds over N independent engines:

- **One front door** — :meth:`predict` routes through a
  :class:`~flinkml_tpu.serving.router.Router`:
  least-outstanding-rows balance, deadline-aware admission, and
  automatic failover of pure transforms.
- **Per-replica degradation** — a replica that trips its queue bound
  drains and rejoins; one that fails its dispatches (e.g. the
  ``serving.replica`` fault seam killing it mid-traffic) is retired
  (stopped without drain, so its queued requests fail fast into the
  router's retry) while the pool keeps serving. No global brownout.
- **Rolling hot-swap** — :meth:`follow_registry` registers ONE pool
  listener and rolls each publish/rollback across the replicas one at a
  time, re-reading the registry's CURRENT pointer at every step: each
  engine's swap is individually zero-downtime, at most one replica is
  warming at any moment (never all down at once), and a rollback racing
  a publish converges every replica to whatever the pointer last said
  (the registry serializes deliveries and re-reads the pointer per
  delivery, so the final roll always carries the newest version).

Metrics: every replica's engine reports into ONE group
(``serving.<pool>``) distinguished by a ``replica`` label, so
per-replica gauges aggregate in the Prometheus exposition instead of
colliding; pool-level routing counters live in ``serving.<pool>.router``.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Union

import numpy as np

from flinkml_tpu.serving.engine import ServingConfig, ServingEngine
from flinkml_tpu.serving.errors import RegistryError
from flinkml_tpu.serving.health import HealthPolicy, ReplicaHealth, ReplicaState
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.serving.router import Router
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("serving.pool")


def slice_meshes(n_slices: int, devices: Optional[Sequence[Any]] = None,
                 plan: Optional[Any] = None) -> List[Any]:
    """Cut the local devices into ``n_slices`` disjoint meshes — the
    per-replica placement for SPMD serving models. Disjoint slices get
    independent ``local_execution_lock``s (replicas dispatch
    concurrently); a slice overlapping a training mesh composes every
    intersecting lock, which is what keeps a pool safe beside training.

    ``plan=None`` keeps the historical 1-D data slices. Passing a
    :class:`~flinkml_tpu.sharding.plan.ShardingPlan` shapes each slice
    for the plan's required axes via ``DeviceMesh.for_plan`` — how a
    pool serves plan-sharded state (e.g. an ``EMBEDDING``-family table
    whose rows shard over each slice's ``fsdp × tp`` product)."""
    import jax

    from flinkml_tpu.parallel import DeviceMesh

    if devices is None:
        devices = jax.devices()
    n_slices = int(n_slices)
    if not 1 <= n_slices <= len(devices):
        raise ValueError(
            f"cannot cut {len(devices)} devices into {n_slices} slices"
        )
    if len(devices) % n_slices:
        # Silently dropping the remainder would quietly serve on fewer
        # devices than the operator provisioned.
        raise ValueError(
            f"{len(devices)} devices do not divide into {n_slices} equal "
            f"slices; pass an explicit devices= subset"
        )
    per = len(devices) // n_slices
    chunks = [list(devices[i * per:(i + 1) * per]) for i in range(n_slices)]
    if plan is not None:
        return [DeviceMesh.for_plan(plan, devices=c) for c in chunks]
    return [
        DeviceMesh({DeviceMesh.DATA_AXIS: per}, devices=c) for c in chunks
    ]


@dataclasses.dataclass
class Replica:
    """One pool slot: a named engine plus its health ledger.
    ``model_id`` is set by multi-model pools (each replica serves ONE
    model; the router filters candidates by it)."""

    name: str
    engine: ServingEngine
    health: ReplicaHealth
    device: Optional[Any] = None
    mesh: Optional[Any] = None
    model_id: Optional[str] = None


class ReplicaPool:
    """See module docstring.

    ``source`` is a :class:`ModelRegistry` (versioned, rolling hot-swap)
    or a fixed transformer stage. Placement, one of:

    - default: one replica per local ``jax.Device`` (``n_replicas``
      caps/repeats over them);
    - ``devices=[...]``: one replica per given device;
    - ``meshes=[...]``: one replica per mesh slice (SPMD models; each
      engine gets ``config.mesh`` and time-shares via the slice lock —
      build slices with :func:`slice_meshes`).

    ``config`` is the per-replica engine template; per-replica queue
    bounds apply per engine, so pool capacity is the sum.
    ``shed_on_overload`` is forced off for replicas — a full replica
    queue fails over to a less-loaded replica (and trips DRAINING after
    enough refusals) instead of serving slowly on the router's thread.
    """

    def __init__(
        self,
        source: Union[ModelRegistry, Any],
        example: Table,
        *,
        config: Optional[ServingConfig] = None,
        n_replicas: Optional[int] = None,
        devices: Optional[Sequence[Any]] = None,
        meshes: Optional[Sequence[Any]] = None,
        output_cols: Optional[Sequence[str]] = None,
        name: str = "pool",
        health_policy: Optional[HealthPolicy] = None,
        share_compiles: bool = True,
        grayfail: Optional["GrayFailPolicy"] = None,
    ):
        if devices is not None and meshes is not None:
            raise ValueError("pass devices= or meshes=, not both")
        # N replicas warm the SAME (program, bucket, policy) identities;
        # without an AOT artifact layer each per-device placement pays
        # its own full XLA compile inside jax.jit (invisible to the
        # fused executor's device-less cache key). share_compiles makes
        # spin-up route through flinkml_tpu.compile_cache — replica 0
        # compiles once, every other replica loads the retargeted
        # artifact — installing a process-local memory store when no
        # persistent one is configured.
        self._init_core(
            source, example, config=config, output_cols=output_cols,
            name=name, health_policy=health_policy,
            share_compiles=share_compiles, grayfail=grayfail,
        )
        placements: List[Dict[str, Any]]
        if meshes is not None:
            placements = [{"mesh": m} for m in meshes]
            self._device_universe = None  # scale-up needs explicit meshes
        else:
            if devices is None:
                import jax

                devices = jax.devices()
            n = int(n_replicas) if n_replicas is not None else len(devices)
            if n < 1:
                raise ValueError(f"n_replicas must be >= 1, got {n}")
            placements = [
                {"device": devices[i % len(devices)]} for i in range(n)
            ]
            # The placement universe scale-ups draw from (round-robin,
            # continuing the initial assignment).
            self._device_universe = list(devices)
        for place in placements:
            self.replicas.append(self._make_replica(place, source))

    def _init_core(self, source: Any, example: Table, *,
                   config: Optional[ServingConfig], output_cols,
                   name: str, health_policy: Optional[HealthPolicy],
                   share_compiles: bool,
                   grayfail: Optional["GrayFailPolicy"] = None) -> None:
        """Everything a pool is besides its initial replica set — shared
        with :class:`~flinkml_tpu.serving.multiplex.MultiModelPool`,
        which starts EMPTY and grows replicas per registered model."""
        self._share_compiles = bool(share_compiles)
        self.name = name
        self._source = source
        self._registry = source if isinstance(source, ModelRegistry) else None
        self._base_config = config or ServingConfig()
        self._device_universe: Optional[List[Any]] = None
        self._schema = {
            c: (np.asarray(example.column(c)).dtype,
                np.asarray(example.column(c)).shape[1:])
            for c in example.column_names
        }
        self._example = example
        self._output_cols = output_cols
        self._health_policy = health_policy or HealthPolicy()
        self.replicas: List[Replica] = []
        self._next_index = 0
        self._metrics = metrics.group(f"serving.{name}.router")
        # Freshness lag gauges: trainer watermark vs what replicas serve
        # (batch counts, no wall clock) — see freshness_lag().
        self._freshness_metrics = metrics.group(f"serving.{name}.freshness")
        from flinkml_tpu.serving.grayfail import GrayFailPolicy

        # Gray-failure defense is on by default: the policy's floors
        # keep it inert at healthy CPU-mesh latencies, so only genuine
        # 10x+ stalls trigger abandonment/hedging/quarantine.
        self.grayfail_policy = grayfail or GrayFailPolicy()
        #: SLO classes currently shed by the brownout ladder (set by a
        #: running GrayFailGuard; multi-model admission consults it).
        self.brownout_shed_classes: frozenset = frozenset()
        self._router = Router(
            self.replicas, self._rows_of, self._metrics,
            on_retire=self._retire,
            grayfail=self.grayfail_policy,
            default_timeout_ms=self._base_config.default_timeout_ms,
            pool_name=name,
        )
        self._roll_lock = threading.RLock()
        self._following = False
        self._started = False

    def set_brownout(self, shed_classes: frozenset) -> None:
        """Install the brownout ladder's current shed set (called by
        :class:`~flinkml_tpu.serving.grayfail.GrayFailGuard`); admission
        for these SLO classes is refused with the typed
        :class:`~flinkml_tpu.serving.errors.SLOAdmissionError` until the
        ladder de-escalates."""
        self.brownout_shed_classes = frozenset(shed_classes)
        if shed_classes:
            _log.warning("pool %s: brownout shedding SLO classes %s",
                         self.name, sorted(shed_classes))

    def grayfail_guard(self, policy: Optional[Any] = None,
                       interval_s: float = 0.25):
        """Build (not start) a gray-failure guard bound to this pool —
        convenience mirroring ``PoolAutoscaler(pool, cfg)``."""
        from flinkml_tpu.serving.grayfail import GrayFailGuard

        return GrayFailGuard(
            self, policy or self.grayfail_policy, interval_s=interval_s
        )

    def _make_replica(self, place: Dict[str, Any], source: Any,
                      model_id: Optional[str] = None) -> Replica:
        """Build (but do not start) one replica slot; advances the name
        counter so scale-ups continue the ``r<i>`` numbering."""
        i = self._next_index
        self._next_index += 1
        rname = f"r{i}"
        cfg = dataclasses.replace(
            self._base_config,
            device=place.get("device"),
            mesh=place.get("mesh"),
            metrics_name=self.name,
            metrics_labels={"replica": rname},
            dispatch_tag=f"serving.pool/{self.name}/{rname}",
            # Replicas never shed to the caller's host path: shedding
            # would serve the request slowly on the ROUTER thread and
            # hide the queue-full signal the per-replica degradation
            # (failover -> DRAINING -> pool overload) is built on.
            # The pool's shed path IS failover to a less-loaded
            # replica.
            shed_on_overload=False,
        )
        engine = ServingEngine(
            source, self._example, cfg, output_cols=self._output_cols,
            name=f"{self.name}/{rname}",
        )
        return Replica(
            name=rname, engine=engine,
            health=ReplicaHealth(rname, self._health_policy),
            device=place.get("device"), mesh=place.get("mesh"),
            model_id=model_id,
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaPool":
        """Start every replica (load + per-bucket warmup, serially — the
        first replica compiles each (program, bucket, policy) once and
        every later replica loads the shared AOT artifact retargeted to
        its own device; see ``share_compiles``). Returns self."""
        if self._share_compiles:
            from flinkml_tpu import compile_cache

            compile_cache.ensure_store()
        for replica in list(self.replicas):  # scaling mutates the list
            replica.engine.start()
        self._started = True
        self._metrics.gauge("replicas", float(len(self.replicas)))
        self._update_health_gauge()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        if self._following and self._registry is not None:
            self._registry.remove_listener(self._on_registry_change)
            self._following = False
        # Snapshot: a still-running autoscaler removing a replica
        # mid-iteration would shift the list and skip one — leaving its
        # dispatcher running after stop() returned.
        for replica in list(self.replicas):
            replica.engine.stop(drain=drain, timeout=timeout)
        self._started = False

    # -- the request path --------------------------------------------------
    def predict(self, features: Union[Table, Mapping[str, Any]],
                timeout_ms: Optional[float] = None):
        """Route one request (same contract as
        :meth:`ServingEngine.predict`, plus failover — see
        :class:`~flinkml_tpu.serving.router.Router`)."""
        return self._router.predict(features, timeout_ms=timeout_ms)

    def _rows_of(self, features: Union[Table, Mapping[str, Any]]) -> int:
        try:
            col, (_, trailing) = next(iter(self._schema.items()))
            a = (features.column(col) if isinstance(features, Table)
                 else features[col])
            a = np.asarray(a)
            return a.shape[0] if a.ndim > len(trailing) else 1
        except Exception:  # noqa: BLE001 — schema errors surface in the engine
            return 1

    # -- degradation -------------------------------------------------------
    def _retire(self, replica: Replica, error: BaseException) -> None:
        """Take a failed replica out of service: stop WITHOUT drain so
        its queued requests fail fast into the router's retry path. Runs
        the stop off-thread — the retiring router thread must not block
        on the dead replica's dispatcher."""
        self._metrics.counter("replicas_retired")
        self._update_health_gauge()
        _log.warning(
            "retiring replica %s/%s after %r; traffic respread over %d "
            "healthy replicas", self.name, replica.name, error,
            len(self.healthy_replicas()),
        )

        def _stop():
            try:
                replica.engine.stop(drain=False, timeout=5.0)
            except Exception:  # noqa: BLE001 — already failed; log only
                _log.exception("stopping retired replica %s", replica.name)

        threading.Thread(
            target=_stop, name=f"retire-{self.name}/{replica.name}",
            daemon=True,
        ).start()

    def revive(self, replica_name: str) -> None:
        """Operator path: restart a retired replica and rejoin rotation
        (re-synced to the registry's current version when following).
        Health stats reset on revive — a revived replica must not be
        ranked by its pre-failure latency/backlog history — and the
        EWMA re-seeds from healthy siblings like a fresh scale-up."""
        replica = self._replica(replica_name)
        replica.engine.start()
        replica.health.revive()
        self._seed_ewma(replica)
        self._update_health_gauge()
        if self._following:
            self._roll_to_current()

    # -- elastic membership (the autoscaler's surface) ---------------------
    def _seed_ewma(self, replica: Replica) -> None:
        """Seed a fresh/revived replica's latency EWMA from the median
        of its healthy siblings, so the router's deadline-aware ordering
        treats it as a known quantity and sends it load immediately
        instead of letting the estimate settle late."""
        values = [
            r.health.ewma_ms_per_row
            for r in self.replicas
            if r is not replica
            and r.health.state is ReplicaState.HEALTHY
            and r.health.ewma_ms_per_row is not None
        ]
        if values:
            replica.health.seed_ewma(float(np.median(values)))

    def add_replica(self, device: Optional[Any] = None,
                    mesh: Optional[Any] = None,
                    source: Optional[Any] = None,
                    model_id: Optional[str] = None) -> Replica:
        """Grow the pool by one replica (the autoscaler's scale-up).

        Placement: an explicit ``device`` or ``mesh``, else the next
        device of the pool's placement universe (round-robin,
        continuing the constructor's assignment; mesh-placed pools must
        pass a mesh). On a started pool the new replica starts — and
        warms — BEFORE joining the routing table, and its warmup rides
        the shared compile-cache store (``share_compiles``): the
        programs the siblings already compiled retarget-load onto the
        new placement, so scale-up pays artifact I/O, not XLA compiles.
        Its latency EWMA seeds from the healthy siblings' median so it
        takes load immediately."""
        if device is None and mesh is None:
            if self._device_universe is None:
                raise ValueError(
                    "mesh-placed pool: pass add_replica(mesh=...) (build "
                    "slices with slice_meshes)"
                )
            device = self._device_universe[
                self._next_index % len(self._device_universe)
            ]
        place = {"device": device, "mesh": mesh}
        replica = self._make_replica(
            place, source if source is not None else self._source,
            model_id=model_id,
        )
        if self._started:
            if self._share_compiles:
                from flinkml_tpu import compile_cache

                compile_cache.ensure_store()
            replica.engine.start()
        self._seed_ewma(replica)
        # Join rotation only once warmed: the router iterates the live
        # list, so the append IS the go-live.
        self.replicas.append(replica)
        self._metrics.counter("replicas_added")
        self._metrics.gauge("replicas", float(len(self.replicas)))
        self._update_health_gauge()
        _log.info("pool %s scaled UP: replica %s on %s (now %d)",
                  self.name, replica.name,
                  device if device is not None else mesh,
                  len(self.replicas))
        return replica

    def remove_replica(self, replica_name: Optional[str] = None,
                       drain: bool = True,
                       timeout: Optional[float] = None) -> str:
        """Shrink the pool by one replica (the autoscaler's scale-down):
        take it out of rotation FIRST (new requests stop routing to it),
        then stop it — with ``drain`` (default) its queued requests
        finish before the engine dies, so scale-down loses nothing.
        Default victim: the healthy replica with the least outstanding
        work (never the last healthy one)."""
        if replica_name is not None:
            replica = self._replica(replica_name)
        else:
            replica = self._scale_down_victim()
        self.replicas.remove(replica)  # out of rotation before the stop
        replica.engine.stop(drain=drain, timeout=timeout)
        self._metrics.counter("replicas_removed")
        self._finish_remove(replica)
        return replica.name

    def prune_retired(self) -> List[str]:
        """Drop UNHEALTHY (retired, already-stopped) replicas from the
        pool. The autoscaler calls this after REPLACING a retirement:
        keeping the dead slot around would leak one stopped engine per
        failure under a flapping fault (and inflate capacity-based
        accounting); an operator who wants the dead engine back instead
        uses :meth:`revive` BEFORE the replacement lands. Returns the
        pruned names."""
        retired = [
            r for r in self.replicas
            if r.health.state is ReplicaState.UNHEALTHY
        ]
        for replica in retired:
            self.replicas.remove(replica)
            # Retirement already stopped the engine (without drain);
            # belt-and-braces for an engine retired mid-stop.
            try:
                replica.engine.stop(drain=False, timeout=1.0)
            except Exception:  # noqa: BLE001 — already dead; log only
                _log.exception("stopping pruned replica %s", replica.name)
        if retired:
            self._metrics.counter("replicas_pruned", float(len(retired)))
            self._metrics.gauge("replicas", float(len(self.replicas)))
            self._update_health_gauge()
            _log.info("pool %s pruned retired replicas: %s", self.name,
                      [r.name for r in retired])
        return [r.name for r in retired]

    def _scale_down_victim(self) -> Replica:
        """Default victim choice: the healthy replica with the least
        outstanding work, never the last healthy one (multi-model pools
        additionally keep every model's last replica)."""
        healthy = [
            r for r in self.replicas
            if r.health.state is ReplicaState.HEALTHY
        ]
        if len(healthy) <= 1:
            raise ValueError(
                f"pool {self.name}: refusing to remove the last "
                "healthy replica"
            )
        return min(healthy, key=lambda r: r.health.outstanding_rows)

    def _finish_remove(self, replica: Replica) -> None:
        self._metrics.gauge("replicas", float(len(self.replicas)))
        self._update_health_gauge()
        _log.info("pool %s scaled DOWN: replica %s removed (now %d)",
                  self.name, replica.name, len(self.replicas))

    def healthy_replicas(self) -> List[Replica]:
        return [
            r for r in list(self.replicas)
            if r.health.state is not ReplicaState.UNHEALTHY
        ]

    def _replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica {name!r} in pool {self.name}")

    def _update_health_gauge(self) -> None:
        healthy = sum(
            1 for r in list(self.replicas)
            if r.health.state is ReplicaState.HEALTHY
        )
        self._metrics.gauge("healthy_replicas", float(healthy))

    # -- rolling hot-swap --------------------------------------------------
    def follow_registry(self) -> "ReplicaPool":
        """Roll every registry publish/rollback across the pool, one
        replica at a time (see module docstring)."""
        if self._registry is None:
            raise RegistryError(
                "follow_registry requires a ModelRegistry-backed pool"
            )
        if not self._following:
            self._registry.add_listener(self._on_registry_change)
            self._following = True
        self._roll_to_current()  # catch up on anything already published
        return self

    def _on_registry_change(self, version: int) -> None:
        self._roll_to_current()

    def _roll_to_current(self) -> None:
        with self._roll_lock:
            for replica in list(self.replicas):  # scaling mutates the list
                if replica.health.state is ReplicaState.UNHEALTHY:
                    continue  # revive() re-syncs it
                # Re-read CURRENT per step: a rollback racing this roll
                # flips the remaining replicas to the rolled-back version
                # mid-roll, and the rollback's own (serialized) delivery
                # converges the early ones — last pointer wins everywhere.
                current = self._registry.current_version()
                if current is None:
                    return
                if replica.engine.active_version != current:
                    replica.engine.swap_to(current)
                    self._metrics.counter("rolled_swaps")
            self.freshness_lag()

    # -- observability -----------------------------------------------------
    def freshness_lag(
        self, trainer_watermark: Optional[int] = None,
    ) -> Optional[int]:
        """How stale the pool is, in source batches: the trainer-side
        edge minus the OLDEST watermark any healthy replica currently
        serves (the worst answer a client can get). The edge is the live
        ``trainer_watermark`` when given (batches the trainer has
        consumed, published or not), else the registry's newest stamped
        watermark. Publishes the ``serving.<pool>.freshness`` gauges
        (``lag_batches`` / ``latest_watermark`` / ``served_watermark_min``)
        and returns the lag — None when the pool is not registry-backed
        or no stamped watermarks exist yet. Deterministic by
        construction: watermarks are batch counts, never wall clocks."""
        if self._registry is None:
            return None
        latest = (int(trainer_watermark) if trainer_watermark is not None
                  else self._registry.latest_watermark())
        if latest is None:
            return None
        served = []
        for r in self.healthy_replicas():
            v = r.engine.active_version
            if v is None:
                continue
            mark = self._registry.watermark_of(v)
            if mark is not None:
                served.append(mark)
        if not served:
            return None
        lag = int(latest) - int(min(served))
        self._freshness_metrics.gauge("latest_watermark", int(latest))
        self._freshness_metrics.gauge("served_watermark_min",
                                      int(min(served)))
        self._freshness_metrics.gauge("lag_batches", lag)
        return lag
    def versions(self) -> Dict[str, Optional[int]]:
        return {r.name: r.engine.active_version for r in list(self.replicas)}

    def stats(self) -> Dict[str, Any]:
        per_replica = {}
        for r in list(self.replicas):
            snap = r.engine._metrics.snapshot()
            per_replica[r.name] = {
                **r.health.snapshot(),
                "engine_running": r.engine.running,
                "active_version": r.engine.active_version,
                "queue_depth": r.engine._batcher.queue_depth,
                "queued_rows": r.engine._batcher.queued_rows,
                "counters": snap["counters"],
                "gauges": snap["gauges"],
            }
        return {
            "name": self.name,
            "replicas": len(self.replicas),
            "healthy": len([
                r for r in list(self.replicas)
                if r.health.state is ReplicaState.HEALTHY
            ]),
            "router": self._metrics.snapshot()["counters"],
            "freshness_lag": self.freshness_lag(),
            "brownout_shed": sorted(self.brownout_shed_classes),
            "per_replica": per_replica,
        }
