"""Replica pool: N serving engines behind one router — serving scale-out.

The reference serves models through Flink's parallel task slots; here
the slot is a :class:`~flinkml_tpu.serving.engine.ServingEngine` replica
and the parallelism substrate is the device plane (ROADMAP item 3). A
:class:`ReplicaPool` spins up one engine per **device** (the fused
executor's single-device programs dispatch lock-free and in parallel —
each replica's dispatcher thread owns one device via
``jax.default_device``) or per **mesh slice** (SPMD models: each replica
holds ``local_execution_lock(slice)`` per batch, so pools time-share
devices with concurrent training exactly like concurrent fits do, and
the slice locks compose through ``parallel.dispatch``'s overlap
machinery — analyzer-checkable, FML303).

What the pool adds over N independent engines:

- **One front door** — :meth:`predict` routes through a
  :class:`~flinkml_tpu.serving.router.Router`:
  least-outstanding-rows balance, deadline-aware admission, and
  automatic failover of pure transforms.
- **Per-replica degradation** — a replica that trips its queue bound
  drains and rejoins; one that fails its dispatches (e.g. the
  ``serving.replica`` fault seam killing it mid-traffic) is retired
  (stopped without drain, so its queued requests fail fast into the
  router's retry) while the pool keeps serving. No global brownout.
- **Rolling hot-swap** — :meth:`follow_registry` registers ONE pool
  listener and rolls each publish/rollback across the replicas one at a
  time, re-reading the registry's CURRENT pointer at every step: each
  engine's swap is individually zero-downtime, at most one replica is
  warming at any moment (never all down at once), and a rollback racing
  a publish converges every replica to whatever the pointer last said
  (the registry serializes deliveries and re-reads the pointer per
  delivery, so the final roll always carries the newest version).

Metrics: every replica's engine reports into ONE group
(``serving.<pool>``) distinguished by a ``replica`` label, so
per-replica gauges aggregate in the Prometheus exposition instead of
colliding; pool-level routing counters live in ``serving.<pool>.router``.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Union

import numpy as np

from flinkml_tpu.serving.engine import ServingConfig, ServingEngine
from flinkml_tpu.serving.errors import RegistryError
from flinkml_tpu.serving.health import HealthPolicy, ReplicaHealth, ReplicaState
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.serving.router import Router
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("serving.pool")


def slice_meshes(n_slices: int, devices: Optional[Sequence[Any]] = None,
                 plan: Optional[Any] = None) -> List[Any]:
    """Cut the local devices into ``n_slices`` disjoint meshes — the
    per-replica placement for SPMD serving models. Disjoint slices get
    independent ``local_execution_lock``s (replicas dispatch
    concurrently); a slice overlapping a training mesh composes every
    intersecting lock, which is what keeps a pool safe beside training.

    ``plan=None`` keeps the historical 1-D data slices. Passing a
    :class:`~flinkml_tpu.sharding.plan.ShardingPlan` shapes each slice
    for the plan's required axes via ``DeviceMesh.for_plan`` — how a
    pool serves plan-sharded state (e.g. an ``EMBEDDING``-family table
    whose rows shard over each slice's ``fsdp × tp`` product)."""
    import jax

    from flinkml_tpu.parallel import DeviceMesh

    if devices is None:
        devices = jax.devices()
    n_slices = int(n_slices)
    if not 1 <= n_slices <= len(devices):
        raise ValueError(
            f"cannot cut {len(devices)} devices into {n_slices} slices"
        )
    if len(devices) % n_slices:
        # Silently dropping the remainder would quietly serve on fewer
        # devices than the operator provisioned.
        raise ValueError(
            f"{len(devices)} devices do not divide into {n_slices} equal "
            f"slices; pass an explicit devices= subset"
        )
    per = len(devices) // n_slices
    chunks = [list(devices[i * per:(i + 1) * per]) for i in range(n_slices)]
    if plan is not None:
        return [DeviceMesh.for_plan(plan, devices=c) for c in chunks]
    return [
        DeviceMesh({DeviceMesh.DATA_AXIS: per}, devices=c) for c in chunks
    ]


@dataclasses.dataclass
class Replica:
    """One pool slot: a named engine plus its health ledger."""

    name: str
    engine: ServingEngine
    health: ReplicaHealth
    device: Optional[Any] = None
    mesh: Optional[Any] = None


class ReplicaPool:
    """See module docstring.

    ``source`` is a :class:`ModelRegistry` (versioned, rolling hot-swap)
    or a fixed transformer stage. Placement, one of:

    - default: one replica per local ``jax.Device`` (``n_replicas``
      caps/repeats over them);
    - ``devices=[...]``: one replica per given device;
    - ``meshes=[...]``: one replica per mesh slice (SPMD models; each
      engine gets ``config.mesh`` and time-shares via the slice lock —
      build slices with :func:`slice_meshes`).

    ``config`` is the per-replica engine template; per-replica queue
    bounds apply per engine, so pool capacity is the sum.
    ``shed_on_overload`` is forced off for replicas — a full replica
    queue fails over to a less-loaded replica (and trips DRAINING after
    enough refusals) instead of serving slowly on the router's thread.
    """

    def __init__(
        self,
        source: Union[ModelRegistry, Any],
        example: Table,
        *,
        config: Optional[ServingConfig] = None,
        n_replicas: Optional[int] = None,
        devices: Optional[Sequence[Any]] = None,
        meshes: Optional[Sequence[Any]] = None,
        output_cols: Optional[Sequence[str]] = None,
        name: str = "pool",
        health_policy: Optional[HealthPolicy] = None,
        share_compiles: bool = True,
    ):
        if devices is not None and meshes is not None:
            raise ValueError("pass devices= or meshes=, not both")
        # N replicas warm the SAME (program, bucket, policy) identities;
        # without an AOT artifact layer each per-device placement pays
        # its own full XLA compile inside jax.jit (invisible to the
        # fused executor's device-less cache key). share_compiles makes
        # spin-up route through flinkml_tpu.compile_cache — replica 0
        # compiles once, every other replica loads the retargeted
        # artifact — installing a process-local memory store when no
        # persistent one is configured.
        self._share_compiles = bool(share_compiles)
        self.name = name
        self._registry = source if isinstance(source, ModelRegistry) else None
        base = config or ServingConfig()
        placements: List[Dict[str, Any]]
        if meshes is not None:
            placements = [{"mesh": m} for m in meshes]
        else:
            if devices is None:
                import jax

                devices = jax.devices()
            n = int(n_replicas) if n_replicas is not None else len(devices)
            if n < 1:
                raise ValueError(f"n_replicas must be >= 1, got {n}")
            placements = [
                {"device": devices[i % len(devices)]} for i in range(n)
            ]
        self._schema = {
            c: (np.asarray(example.column(c)).dtype,
                np.asarray(example.column(c)).shape[1:])
            for c in example.column_names
        }
        policy = health_policy or HealthPolicy()
        self.replicas: List[Replica] = []
        for i, place in enumerate(placements):
            rname = f"r{i}"
            cfg = dataclasses.replace(
                base,
                device=place.get("device"),
                mesh=place.get("mesh"),
                metrics_name=name,
                metrics_labels={"replica": rname},
                dispatch_tag=f"serving.pool/{name}/{rname}",
                # Replicas never shed to the caller's host path: shedding
                # would serve the request slowly on the ROUTER thread and
                # hide the queue-full signal the per-replica degradation
                # (failover -> DRAINING -> pool overload) is built on.
                # The pool's shed path IS failover to a less-loaded
                # replica.
                shed_on_overload=False,
            )
            engine = ServingEngine(
                source, example, cfg, output_cols=output_cols,
                name=f"{name}/{rname}",
            )
            self.replicas.append(Replica(
                name=rname, engine=engine,
                health=ReplicaHealth(rname, policy),
                device=place.get("device"), mesh=place.get("mesh"),
            ))
        self._metrics = metrics.group(f"serving.{name}.router")
        self._router = Router(
            self.replicas, self._rows_of, self._metrics,
            on_retire=self._retire,
        )
        self._roll_lock = threading.RLock()
        self._following = False
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaPool":
        """Start every replica (load + per-bucket warmup, serially — the
        first replica compiles each (program, bucket, policy) once and
        every later replica loads the shared AOT artifact retargeted to
        its own device; see ``share_compiles``). Returns self."""
        if self._share_compiles:
            from flinkml_tpu import compile_cache

            compile_cache.ensure_store()
        for replica in self.replicas:
            replica.engine.start()
        self._started = True
        self._metrics.gauge("replicas", float(len(self.replicas)))
        self._update_health_gauge()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        if self._following and self._registry is not None:
            self._registry.remove_listener(self._on_registry_change)
            self._following = False
        for replica in self.replicas:
            replica.engine.stop(drain=drain, timeout=timeout)
        self._started = False

    # -- the request path --------------------------------------------------
    def predict(self, features: Union[Table, Mapping[str, Any]],
                timeout_ms: Optional[float] = None):
        """Route one request (same contract as
        :meth:`ServingEngine.predict`, plus failover — see
        :class:`~flinkml_tpu.serving.router.Router`)."""
        return self._router.predict(features, timeout_ms=timeout_ms)

    def _rows_of(self, features: Union[Table, Mapping[str, Any]]) -> int:
        try:
            col, (_, trailing) = next(iter(self._schema.items()))
            a = (features.column(col) if isinstance(features, Table)
                 else features[col])
            a = np.asarray(a)
            return a.shape[0] if a.ndim > len(trailing) else 1
        except Exception:  # noqa: BLE001 — schema errors surface in the engine
            return 1

    # -- degradation -------------------------------------------------------
    def _retire(self, replica: Replica, error: BaseException) -> None:
        """Take a failed replica out of service: stop WITHOUT drain so
        its queued requests fail fast into the router's retry path. Runs
        the stop off-thread — the retiring router thread must not block
        on the dead replica's dispatcher."""
        self._metrics.counter("replicas_retired")
        self._update_health_gauge()
        _log.warning(
            "retiring replica %s/%s after %r; traffic respread over %d "
            "healthy replicas", self.name, replica.name, error,
            len(self.healthy_replicas()),
        )

        def _stop():
            try:
                replica.engine.stop(drain=False, timeout=5.0)
            except Exception:  # noqa: BLE001 — already failed; log only
                _log.exception("stopping retired replica %s", replica.name)

        threading.Thread(
            target=_stop, name=f"retire-{self.name}/{replica.name}",
            daemon=True,
        ).start()

    def revive(self, replica_name: str) -> None:
        """Operator path: restart a retired replica and rejoin rotation
        (re-synced to the registry's current version when following)."""
        replica = self._replica(replica_name)
        replica.engine.start()
        replica.health.revive()
        self._update_health_gauge()
        if self._following:
            self._roll_to_current()

    def healthy_replicas(self) -> List[Replica]:
        return [
            r for r in self.replicas
            if r.health.state is not ReplicaState.UNHEALTHY
        ]

    def _replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica {name!r} in pool {self.name}")

    def _update_health_gauge(self) -> None:
        healthy = sum(
            1 for r in self.replicas
            if r.health.state is ReplicaState.HEALTHY
        )
        self._metrics.gauge("healthy_replicas", float(healthy))

    # -- rolling hot-swap --------------------------------------------------
    def follow_registry(self) -> "ReplicaPool":
        """Roll every registry publish/rollback across the pool, one
        replica at a time (see module docstring)."""
        if self._registry is None:
            raise RegistryError(
                "follow_registry requires a ModelRegistry-backed pool"
            )
        if not self._following:
            self._registry.add_listener(self._on_registry_change)
            self._following = True
        self._roll_to_current()  # catch up on anything already published
        return self

    def _on_registry_change(self, version: int) -> None:
        self._roll_to_current()

    def _roll_to_current(self) -> None:
        with self._roll_lock:
            for replica in self.replicas:
                if replica.health.state is ReplicaState.UNHEALTHY:
                    continue  # revive() re-syncs it
                # Re-read CURRENT per step: a rollback racing this roll
                # flips the remaining replicas to the rolled-back version
                # mid-roll, and the rollback's own (serialized) delivery
                # converges the early ones — last pointer wins everywhere.
                current = self._registry.current_version()
                if current is None:
                    return
                if replica.engine.active_version != current:
                    replica.engine.swap_to(current)
                    self._metrics.counter("rolled_swaps")

    # -- observability -----------------------------------------------------
    def versions(self) -> Dict[str, Optional[int]]:
        return {r.name: r.engine.active_version for r in self.replicas}

    def stats(self) -> Dict[str, Any]:
        per_replica = {}
        for r in self.replicas:
            snap = r.engine._metrics.snapshot()
            per_replica[r.name] = {
                **r.health.snapshot(),
                "engine_running": r.engine.running,
                "active_version": r.engine.active_version,
                "queue_depth": r.engine._batcher.queue_depth,
                "queued_rows": r.engine._batcher.queued_rows,
                "counters": snap["counters"],
                "gauges": snap["gauges"],
            }
        return {
            "name": self.name,
            "replicas": len(self.replicas),
            "healthy": len([
                r for r in self.replicas
                if r.health.state is ReplicaState.HEALTHY
            ]),
            "router": self._metrics.snapshot()["counters"],
            "per_replica": per_replica,
        }
