"""Mid-stream model publication: training loops emit serving snapshots.

VERDICT round 5 flagged the one remaining semantic gap vs the reference:
the unbounded iteration mode could "neither checkpoint nor emit a model
before its stream ends", while the reference's unbounded ``Iterations``
feeds per-round models to downstream consumers. :class:`SnapshotPublisher`
closes it from the listener side: attach it to any epoch loop that fires
:class:`~flinkml_tpu.iteration.IterationListener` callbacks —
:func:`flinkml_tpu.iteration.iterate` (bounded or unbounded) or the
hand-rolled stream trainers (``train_kmeans_stream(listeners=[...])``) —
and every N epochs the loop's state becomes a **versioned, fingerprinted
model in a registry**, without stopping the stream.

Consistency: the publisher declares ``needs_materialized_state``, so the
runtime blocks on the loop carry before the callback
(``iteration.runtime.notify_epoch_listeners``) — the snapshot is a fully
computed value, never an in-flight async future.

Zero-downtime path to production: point a
:class:`~flinkml_tpu.serving.engine.ServingEngine` at the same registry
with ``follow_registry()`` (or pass ``engine=`` here) and every publish
hot-swaps the live engine; in-flight batches finish on the old version,
new requests route to the new one.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from flinkml_tpu.iteration.runtime import IterationListener
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.utils.metrics import metrics


class SnapshotPublisher(IterationListener):
    """Publish ``make_model(state)`` into ``registry`` every N epochs.

    Args:
        registry: destination :class:`ModelRegistry`.
        make_model: maps the (materialized) loop state to a save-able
            stage — e.g. centroids → a fitted ``KMeansModel``, or a whole
            ``PipelineModel`` with the fresh model spliced in. Runs on
            the training thread; keep it cheap.
        every_n_epochs: publication cadence (epoch E publishes when
            ``(E + 1) % every_n_epochs == 0``).
        publish_on_terminate: also publish the final state at stream end
            unless the last epoch already published it.
        engine: optional :class:`~flinkml_tpu.serving.engine.ServingEngine`
            to hot-swap after each publish. Redundant (and wasteful —
            double load + warmup) if that engine already
            ``follow_registry()``s this registry; use one or the other.

    ``published`` records ``(epoch, version)`` pairs, newest last.

    Publication is **idempotent across restarts**: each publish carries a
    dedupe key of ``epoch`` + the content fingerprint of the
    (materialized) loop state, recorded atomically with the version. A
    trainer that crashes after publishing epoch E and resumes from the
    epoch-E checkpoint will re-reach the same publish point with the
    same state — the registry returns the already-committed version
    instead of growing a duplicate (see ``ModelRegistry.publish``'s
    ``dedupe_key``).
    """

    needs_materialized_state = True

    def __init__(
        self,
        registry: ModelRegistry,
        make_model: Callable[[Any], Any],
        every_n_epochs: int = 1,
        publish_on_terminate: bool = True,
        engine: Optional[Any] = None,
    ):
        if every_n_epochs < 1:
            raise ValueError(
                f"every_n_epochs must be >= 1, got {every_n_epochs}"
            )
        self.registry = registry
        self.make_model = make_model
        self.every_n_epochs = int(every_n_epochs)
        self.publish_on_terminate = bool(publish_on_terminate)
        self.engine = engine
        self.published: List[Tuple[int, int]] = []
        self._last_published_epoch: Optional[int] = None
        self._epochs_seen = 0
        self._metrics = metrics.group("serving.publisher")

    def wants_epoch_state(self, epoch: int) -> bool:
        """Only publishing epochs need a materialized state — the runtime
        skips the device sync on the others."""
        return (epoch + 1) % self.every_n_epochs == 0

    def on_epoch_watermark_incremented(self, epoch: int, state: Any) -> None:
        self._epochs_seen = max(self._epochs_seen, epoch + 1)
        if (epoch + 1) % self.every_n_epochs:
            return
        self._publish(epoch, state)

    def on_iteration_terminated(self, state: Any) -> None:
        last_epoch = self._epochs_seen - 1
        if not self.publish_on_terminate:
            return
        if last_epoch >= 0 and self._last_published_epoch == last_epoch:
            return  # the final epoch's snapshot is already out
        self._publish(max(last_epoch, 0), state)

    def _publish(self, epoch: int, state: Any) -> None:
        key = self._dedupe_key(epoch, state)
        if key is not None:
            existing = self.registry.find_dedupe(key)
            if existing is not None:
                # Resume re-reached an already-published epoch: record it,
                # skip make_model + save — but an attached engine must
                # still land on this version (it may be serving whatever
                # predated the restart).
                self.published.append((epoch, existing))
                self._last_published_epoch = epoch
                self._metrics.counter("snapshots_deduped")
                if self.engine is not None:
                    self.engine.swap_to(existing)
                return
        model = self.make_model(state)
        version = self.registry.publish(model, dedupe_key=key)
        self.published.append((epoch, version))
        self._last_published_epoch = epoch
        self._metrics.counter("snapshots_published")
        self._metrics.gauge("last_published_version", version)
        if self.engine is not None:
            self.engine.swap_to(version)

    @staticmethod
    def _dedupe_key(epoch: int, state: Any) -> Optional[str]:
        """``epoch`` + content fingerprint of the loop state — identical
        on a resumed run that re-reaches the same publish point. None
        (publish unconditionally) for states that cannot be fingerprinted
        (non-array leaves)."""
        import jax

        from flinkml_tpu.io.read_write import content_fingerprint

        try:
            leaves = jax.tree_util.tree_flatten(state)[0]
            fp = content_fingerprint(
                {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
            )
        except Exception:  # noqa: BLE001 — dedupe is best-effort
            return None
        return f"epoch={epoch}:fp={fp}"
