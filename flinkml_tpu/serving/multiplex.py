"""Multi-model multiplexing with SLO-weighted admission (ROADMAP item 3).

One :class:`MultiModelPool` serves N models over ONE replica pool and one
device universe. Each registered model gets its own source (registry or
fixed stage), its own replicas (each replica serves exactly one model —
the fused executor's programs are per-chain, so mixing models in one
batch is never possible anyway), and an :class:`SLOClass` that states how
the model's traffic shares the pool:

- ``deadline_ms`` — the class's default per-request deadline budget
  (interactive requests get a short one and fail fast; batch requests
  get a long one and wait their turn).
- ``max_queue_share`` — the fraction of AGGREGATE pool queue capacity
  the class may hold in flight. This is the anti-starvation mechanism,
  enforced at ADMISSION in :meth:`MultiModelPool.predict`: a batch class
  capped at 0.5 can never occupy more than half the pool's queue slots
  OR more than its bounded share of the device plane's time (in-flight
  rows are what contend for dispatch), so the interactive tier always
  has admission headroom and bounded queue-wait no matter how hard a
  batch job pushes. Refusals are the typed
  :class:`~flinkml_tpu.serving.errors.SLOAdmissionError` — a batch
  client backing off is the system working, not an incident.
- ``weight`` — the class's priority for SCALING decisions: the
  autoscaler's multi-model target picks the model with the highest
  weight × backlog, so a contended interactive model receives new
  replicas before a contended batch model
  (:meth:`MultiModelPool.scale_target`).

Routing stays the pool's least-outstanding-rows balance, filtered to the
target model's replicas (``Router.predict(model_id=...)``); failover,
per-replica degradation, and retirement are inherited unchanged. Every
model with a registry source participates in rolling hot-swaps
independently (:meth:`MultiModelPool.follow_registries`).

Per-class observability (``serving.<pool>.admission``, one labeled group
per class): ``admitted_requests`` / ``admitted_rows`` /
``budget_rejections`` counters, ``outstanding_rows`` and per-class
``p50_ms`` / ``p99_ms`` latency gauges — the per-class-SLO dashboards'
families. See ``docs/operators/serving.md``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Union

from flinkml_tpu.serving.engine import ServingConfig
from flinkml_tpu.serving.errors import RegistryError, SLOAdmissionError
from flinkml_tpu.serving.health import HealthPolicy, ReplicaState
from flinkml_tpu.serving.pool import Replica, ReplicaPool
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import LatencyWindow, metrics

_log = get_logger("serving.multiplex")


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One service-level class (see module docstring)."""

    name: str
    weight: float = 1.0
    deadline_ms: Optional[float] = None
    max_queue_share: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"SLO class {self.name!r}: weight must be > 0")
        if not 0.0 < self.max_queue_share <= 1.0:
            raise ValueError(
                f"SLO class {self.name!r}: max_queue_share must be in "
                f"(0, 1], got {self.max_queue_share}"
            )


#: The latency tier: full pool access, short deadline budget, priority
#: weight for scaling.
INTERACTIVE = SLOClass(
    "interactive", weight=3.0, deadline_ms=1000.0, max_queue_share=1.0
)

#: The throughput tier: long deadline budget, capped at half the pool's
#: capacity so it can NEVER starve the interactive tier.
BATCH = SLOClass(
    "batch", weight=1.0, deadline_ms=30_000.0, max_queue_share=0.5
)


@dataclasses.dataclass
class _ModelEntry:
    model_id: str
    source: Any
    slo: SLOClass
    registry: Optional[ModelRegistry]


class _ClassLedger:
    """Per-class in-flight accounting + latency window (thread-safe)."""

    def __init__(self, pool_name: str, slo: SLOClass, window: int = 2048):
        self.slo = slo
        self.outstanding_rows = 0
        self._lock = threading.Lock()
        self.metrics = metrics.group(
            f"serving.{pool_name}.admission",
            labels={"slo_class": slo.name},
        )
        # The ONE p50/p99 gauge implementation, shared with the engine
        # (utils.metrics.LatencyWindow) — per-class dashboards must
        # never disagree with per-engine ones about the same traffic.
        self._latency = LatencyWindow(self.metrics, window)

    def try_admit(self, rows: int, budget_rows: float) -> bool:
        with self._lock:
            if self.outstanding_rows + rows > budget_rows:
                return False
            self.outstanding_rows += rows
        self.metrics.counter("admitted_requests")
        self.metrics.counter("admitted_rows", float(rows))
        self.metrics.gauge("outstanding_rows", float(self.outstanding_rows))
        return True

    def settle(self, rows: int) -> None:
        with self._lock:
            self.outstanding_rows = max(0, self.outstanding_rows - rows)
        self.metrics.gauge("outstanding_rows", float(self.outstanding_rows))

    def record_latency(self, latency_ms: float) -> None:
        self._latency.record(latency_ms)


class MultiModelPool(ReplicaPool):
    """N registries over one pool — see module docstring.

    Starts EMPTY; register models with :meth:`add_model`, then
    :meth:`start`. ``example`` fixes the request schema shared by every
    model (multi-tenant fronts serve one feature schema; register
    another pool for another schema)."""

    def __init__(
        self,
        example: Table,
        *,
        config: Optional[ServingConfig] = None,
        devices: Optional[List[Any]] = None,
        name: str = "mmpool",
        health_policy: Optional[HealthPolicy] = None,
        share_compiles: bool = True,
        grayfail: Optional[Any] = None,
    ):
        self._init_core(
            None, example, config=config, output_cols=None,
            name=name, health_policy=health_policy,
            share_compiles=share_compiles, grayfail=grayfail,
        )
        if devices is None:
            import jax

            devices = jax.devices()
        self._device_universe = list(devices)
        self._models: Dict[str, _ModelEntry] = {}
        self._ledgers: Dict[str, _ClassLedger] = {}

    # -- model registration ------------------------------------------------
    def add_model(self, model_id: str, source: Any,
                  slo: SLOClass = INTERACTIVE,
                  n_replicas: int = 1) -> None:
        """Register one model (a :class:`ModelRegistry` or fixed stage)
        under an SLO class, with ``n_replicas`` initial replicas placed
        round-robin on the pool's device universe. Call before or after
        :meth:`start` — replicas added to a started pool warm via the
        shared compile cache like any scale-up."""
        if model_id in self._models:
            raise ValueError(f"model {model_id!r} already registered")
        entry = _ModelEntry(
            model_id=model_id, source=source, slo=slo,
            registry=source if isinstance(source, ModelRegistry) else None,
        )
        self._models[model_id] = entry
        if slo.name not in self._ledgers:
            self._ledgers[slo.name] = _ClassLedger(self.name, slo)
        for _ in range(int(n_replicas)):
            self.add_replica(source=source, model_id=model_id)

    def models(self) -> Dict[str, SLOClass]:
        return {mid: e.slo for mid, e in self._models.items()}

    def _entry(self, model_id: str) -> _ModelEntry:
        try:
            return self._models[model_id]
        except KeyError:
            raise KeyError(
                f"no model {model_id!r} in pool {self.name} (registered: "
                f"{sorted(self._models)})"
            ) from None

    # -- the request path --------------------------------------------------
    def predict(self, model_id: str,
                features: Union[Table, Mapping[str, Any]],
                timeout_ms: Optional[float] = None):
        """Route one request to ``model_id``'s replicas, under its SLO
        class's admission budget and deadline (an explicit
        ``timeout_ms`` wins over the class default). Raises the typed
        :class:`~flinkml_tpu.serving.errors.SLOAdmissionError` when the
        class's capacity share is fully in flight."""
        entry = self._entry(model_id)
        ledger = self._ledgers[entry.slo.name]
        if entry.slo.name in self.brownout_shed_classes:
            # Brownout ladder: under pool-WIDE degradation the guard
            # sheds whole SLO classes in declared order (batch first)
            # so the surviving tiers keep their latency — the typed
            # refusal batch clients already know how to back off from.
            ledger.metrics.counter("brownout_rejections")
            raise SLOAdmissionError(
                f"SLO class {entry.slo.name!r} is shed by the pool's "
                "brownout ladder (pool-wide degradation); back off and "
                "retry"
            )
        rows = self._rows_of(features)
        budget = entry.slo.max_queue_share * self._total_capacity()
        if not ledger.try_admit(rows, budget):
            ledger.metrics.counter("budget_rejections")
            raise SLOAdmissionError(
                f"SLO class {entry.slo.name!r} has its full "
                f"{entry.slo.max_queue_share:.0%} share of pool capacity "
                f"({budget:.0f} rows) in flight; back off and retry"
            )
        # Untimed requests inherit a FINITE deadline: the class default,
        # else the pool-level knob — a stalled replica must never hold a
        # caller (and its admission share) forever.
        timeout = (
            timeout_ms if timeout_ms is not None else entry.slo.deadline_ms
        )
        if timeout is None:
            timeout = self._base_config.default_timeout_ms
        t0 = time.monotonic()
        try:
            # The ledger releases in the finally: with per-attempt
            # abandonment this is ABANDONMENT time, not straggler
            # completion time — router.predict returns/raises the moment
            # it stops waiting, never when a stalled replica finishes.
            # Hedges are admitted once (here), never per attempt.
            resp = self._router.predict(
                features, timeout_ms=timeout, model_id=model_id
            )
        finally:
            ledger.settle(rows)
        ledger.record_latency((time.monotonic() - t0) * 1000.0)
        return resp

    def _total_capacity(self) -> float:
        # LIVE capacity only: counting retired (UNHEALTHY, stopped)
        # replicas would let a capped class occupy 100% of what is
        # actually serving — the exact starvation the share cap exists
        # to prevent.
        return float(sum(
            r.engine.config.max_queue_rows for r in self.replicas
            if r.health.state is not ReplicaState.UNHEALTHY
        )) or 1.0

    # -- scaling hooks (consumed by PoolAutoscaler) ------------------------
    def scale_target(self) -> Dict[str, Any]:
        """The neediest model for the next scale-up: highest SLO weight
        × per-model backlog fraction (ties: fewest replicas). Returns
        ``add_replica`` kwargs."""
        best_id, best_score = None, -1.0
        # Snapshot: add_model() may insert concurrently (the autoscaler
        # thread iterates here).
        for mid, entry in list(self._models.items()):
            mine = [r for r in self.replicas if r.model_id == mid]
            healthy = [
                r for r in mine if r.health.state is ReplicaState.HEALTHY
            ]
            capacity = sum(
                r.engine.config.max_queue_rows for r in healthy
            ) or 1.0
            queued = sum(
                max(r.health.outstanding_rows, r.engine.queued_rows)
                for r in healthy
            )
            backlog = queued / capacity
            # A model with NO healthy replica is the neediest of all.
            score = entry.slo.weight * (
                backlog if healthy else float("inf")
            )
            if score > best_score or (
                score == best_score and best_id is not None
                and len(mine) < len([
                    r for r in self.replicas if r.model_id == best_id
                ])
            ):
                best_id, best_score = mid, score
        if best_id is None:
            return {}
        entry = self._models[best_id]
        return {"source": entry.source, "model_id": best_id}

    def _scale_down_victim(self) -> Replica:
        """Never remove a model's LAST replica: victims come from models
        with >= 2 healthy replicas, least-loaded first, lowest SLO
        weight first among equals."""
        per_model: Dict[str, int] = {}
        for r in self.replicas:
            if r.health.state is ReplicaState.HEALTHY:
                per_model[r.model_id] = per_model.get(r.model_id, 0) + 1
        candidates = [
            r for r in self.replicas
            if r.health.state is ReplicaState.HEALTHY
            and per_model.get(r.model_id, 0) >= 2
        ]
        if not candidates:
            raise ValueError(
                f"pool {self.name}: every model is at its last healthy "
                "replica; refusing scale-down"
            )
        def rank(r: Replica):
            slo = self._models[r.model_id].slo if r.model_id in self._models \
                else INTERACTIVE
            return (r.health.outstanding_rows, slo.weight)
        return min(candidates, key=rank)

    # -- rolling hot-swap (per model) --------------------------------------
    def follow_registry(self) -> "MultiModelPool":
        return self.follow_registries()

    def follow_registries(self) -> "MultiModelPool":
        """Roll every model registry's publishes/rollbacks across THAT
        model's replicas, one at a time (the single-model pool's rolling
        contract, per tenant)."""
        any_registry = False
        for mid, entry in list(self._models.items()):
            if entry.registry is None:
                continue
            any_registry = True
            if getattr(entry, "_listener", None) is None:
                listener = (lambda version, mid=mid: self._roll_model(mid))
                entry.registry.add_listener(listener)
                entry._listener = listener
            self._roll_model(mid)
        if not any_registry:
            raise RegistryError(
                "follow_registries requires at least one "
                "ModelRegistry-backed model"
            )
        self._following = True
        return self

    def _roll_model(self, model_id: str) -> None:
        entry = self._entry(model_id)
        if entry.registry is None:
            return
        with self._roll_lock:
            for replica in list(self.replicas):
                if replica.model_id != model_id:
                    continue
                if replica.health.state is ReplicaState.UNHEALTHY:
                    continue
                current = entry.registry.current_version()
                if current is None:
                    return
                if replica.engine.active_version != current:
                    replica.engine.swap_to(current)
                    self._metrics.counter("rolled_swaps")

    def revive(self, replica_name: str) -> None:
        """Operator path, model-aware: the base revive would re-sync
        through the pool-level registry — always None here (models
        carry their own). Restart + health reset + sibling EWMA seed
        are inherited semantics; the version re-sync happens through
        the replica's OWN model registry (``engine.start`` reloads
        CURRENT, and a followed registry re-rolls the model)."""
        replica = self._replica(replica_name)
        replica.engine.start()
        replica.health.revive()
        self._seed_ewma(replica)
        self._update_health_gauge()
        if replica.model_id in self._models:
            entry = self._models[replica.model_id]
            if entry.registry is not None:
                self._roll_model(replica.model_id)

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        # Registry listeners are per model here, not the base pool's
        # single-source listener — unfollow each, then delegate (the
        # base's registry branch is a no-op with _registry=None, and
        # its replica-stop semantics must not be forked).
        for entry in self._models.values():
            listener = getattr(entry, "_listener", None)
            if listener is not None and entry.registry is not None:
                entry.registry.remove_listener(listener)
                entry._listener = None
        self._following = False
        super().stop(drain=drain, timeout=timeout)

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        base = super().stats()
        base["models"] = {
            mid: {
                "slo_class": e.slo.name,
                "weight": e.slo.weight,
                "replicas": [
                    r.name for r in self.replicas if r.model_id == mid
                ],
            }
            for mid, e in self._models.items()
        }
        base["classes"] = {
            name: {
                "outstanding_rows": ledger.outstanding_rows,
                "max_queue_share": ledger.slo.max_queue_share,
                "counters": ledger.metrics.snapshot()["counters"],
                "gauges": ledger.metrics.snapshot()["gauges"],
            }
            for name, ledger in self._ledgers.items()
        }
        return base
