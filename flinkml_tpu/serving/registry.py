"""Versioned model registry with an atomic "current" pointer.

Layout (on top of the stage persistence format of
:mod:`flinkml_tpu.io.read_write` — any save/load-able Stage publishes,
including whole :class:`~flinkml_tpu.pipeline.PipelineModel` chains)::

    <root>/
      versions/
        000001/           # a saved stage directory (metadata + data/)
        000002/
      CURRENT             # JSON {"version": 2, "timestamp": ...}

Publication is crash-safe in two steps: the stage saves into a hidden
temp directory that is ``os.rename``d to its final numbered home (a
half-written save can never be listed as a version), then ``CURRENT`` is
replaced atomically (``os.replace`` of a temp file — the symlink-swap
idiom without symlinks, portable to filesystems that lack them). Readers
therefore always observe either the old or the new pointer, never a torn
state — the property the serving engine's zero-downtime hot swap rests
on.

Integrity: every model saved through ``Model._save_with_arrays`` records
a sha256 content fingerprint in its metadata, and :meth:`ModelRegistry.get`
loads through the standard stage loader, which verifies it — a corrupt or
tampered snapshot raises
:class:`~flinkml_tpu.io.read_write.ModelIntegrityError` instead of being
swapped into a live engine.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import warnings
from typing import Any, Callable, List, Optional, Tuple

import flinkml_tpu.faults as faults
from flinkml_tpu.io import read_write
from flinkml_tpu.serving.errors import (
    DeltaChainError,
    ModelVersionNotFoundError,
    RegistryError,
)
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("serving.registry")

CURRENT_FILE = "CURRENT"
VERSIONS_DIR = "versions"
PUBLISH_TAG_FILE = "PUBLISH_TAG"
WATERMARK_FILE = "WATERMARK"
_TMP_PREFIX = ".tmp-"


class ModelRegistry:
    """Thread-safe versioned store of published models.

    ``publish`` assigns monotonically increasing integer versions (or
    honors an explicit one), ``get`` loads the current (or a pinned)
    version, ``rollback`` repoints ``CURRENT`` at an existing older
    version without touching its files. Listeners registered via
    :meth:`add_listener` are invoked with the new current version after
    every successful publish/rollback — the serving engine's auto-swap
    hook.
    """

    def __init__(self, root: str):
        self.root = root
        self._versions_root = os.path.join(root, VERSIONS_DIR)
        os.makedirs(self._versions_root, exist_ok=True)
        self._lock = threading.RLock()
        self._notify_lock = threading.Lock()
        self._listeners: List[Callable[[int], None]] = []
        self._metrics = metrics.group("serving.registry")
        # dedupe-key index: version -> key for scanned versions (lazily
        # extended; a fresh instance after a restart rescans from disk, so
        # idempotence survives the process that published dying).
        self._dedupe_keys: dict = {}
        self._dedupe_scanned: set = set()
        # version -> source-batch watermark (immutable once published, so
        # plain memoization; None is cached for unstamped versions).
        self._watermarks: dict = {}

    # -- introspection -----------------------------------------------------
    def versions(self) -> List[int]:
        """Sorted list of published version numbers (complete saves only:
        a version exists once its directory has stage metadata)."""
        out = []
        for name in os.listdir(self._versions_root):
            if name.startswith(_TMP_PREFIX) or not name.isdigit():
                continue
            if os.path.exists(os.path.join(
                    self._versions_root, name, read_write.METADATA_FILE)):
                out.append(int(name))
        return sorted(out)

    def current_version(self) -> Optional[int]:
        """The version ``CURRENT`` points at, or None before any publish."""
        try:
            with open(os.path.join(self.root, CURRENT_FILE)) as f:
                return int(json.load(f)["version"])
        except FileNotFoundError:
            return None

    def path_of(self, version: int) -> str:
        return os.path.join(self._versions_root, f"{int(version):06d}")

    def find_dedupe(self, dedupe_key: str) -> Optional[int]:
        """The version already published under ``dedupe_key``, or None.

        Keys are recorded atomically with the version's files (the tag
        file rides the same rename), so a restarted publisher — even a
        fresh process — sees exactly the publishes that committed."""
        with self._lock:
            for v in self.versions():
                if v in self._dedupe_scanned:
                    continue
                self._dedupe_scanned.add(v)
                tag = os.path.join(self.path_of(v), PUBLISH_TAG_FILE)
                try:
                    with open(tag) as f:
                        self._dedupe_keys[v] = json.load(f)["dedupeKey"]
                except (OSError, ValueError, KeyError):
                    continue  # untagged (or pre-dedupe) version
            for v, key in self._dedupe_keys.items():
                if key == dedupe_key:
                    return v
        return None

    # -- writes ------------------------------------------------------------
    def publish(self, stage: Any, version: Optional[int] = None,
                dedupe_key: Optional[str] = None,
                check_finite: bool = True,
                watermark: Optional[int] = None) -> int:
        """Save ``stage`` as a new version and repoint ``CURRENT`` at it.

        ``check_finite`` (default on) refuses a model whose learned
        arrays hold non-finite values with a typed
        :class:`~flinkml_tpu.recovery.NonFiniteModelError` BEFORE any
        file is written — a NaN'd model must never become a registry
        version a follower could hot-swap into a live engine (the
        publish half of the self-healing contract,
        ``docs/development/fault_tolerance.md``).

        Returns the assigned version. The version number is claimed by an
        atomic ``mkdir`` of the final directory — safe against concurrent
        publishers in other THREADS and other PROCESSES sharing the
        registry root (e.g. per-rank SnapshotPublishers): a taken number
        bumps to the next free one. The save lands in a temp directory
        renamed over the (empty) claimed directory, so readers never see
        a partial version; the pointer flip is atomic (concurrent
        cross-process publishes leave CURRENT at whichever publish
        flipped it last). Raises :class:`RegistryError` when an explicit
        ``version`` already exists.

        ``dedupe_key`` makes publication idempotent: when a committed
        version already carries the key (same epoch + content
        fingerprint — see :class:`~flinkml_tpu.serving.publisher.
        SnapshotPublisher`), that version is returned and NOTHING is
        written — the resume-then-republish path cannot grow duplicate
        versions.

        ``watermark`` stamps the version with its source-batch watermark
        (a ``WATERMARK`` file that rides the same atomic rename as the
        save) — the freshness currency :meth:`watermark_of` and the
        pool's ``serving.<pool>.freshness`` gauge read. Stages that are
        incremental deltas (``is_model_delta``) are counted separately
        (``delta_publishes``) and resolved against their base chain at
        :meth:`get` time."""
        if check_finite:
            # Outside the lock (pure read of the stage), before the seam:
            # a refused publish never counts as a fault-plan event.
            from flinkml_tpu.recovery.sentinel import check_stage_finite

            check_stage_finite(stage, where="publish")
        with self._lock:
            if faults.ACTIVE is not None:  # dropped-publish seam
                faults.fire("registry.publish", root=self.root,
                            version=-1 if version is None else int(version))
            if dedupe_key is not None:
                existing = self.find_dedupe(dedupe_key)
                if existing is not None:
                    self._metrics.counter("publishes_deduped")
                    _log.info(
                        "publish deduplicated: key %r already committed as "
                        "version %d", dedupe_key, existing,
                    )
                    return existing
            v = None if version is None else int(version)
            candidate = v
            if candidate is None:
                existing = self.versions()
                candidate = existing[-1] + 1 if existing else 1
            while True:
                final = self.path_of(candidate)
                try:
                    os.mkdir(final)  # atomic cross-process claim
                    break
                except FileExistsError:
                    if v is not None:
                        raise RegistryError(
                            f"version {v} already exists in registry "
                            f"{self.root}"
                        )
                    candidate += 1
            v = candidate
            tmp = os.path.join(self._versions_root, f"{_TMP_PREFIX}{v:06d}")
            if os.path.exists(tmp):  # leftover of a crashed publish
                shutil.rmtree(tmp)
            try:
                stage.save(tmp)
                if dedupe_key is not None:
                    # Written INSIDE the temp dir: the tag commits in the
                    # same atomic rename as the version itself.
                    with open(os.path.join(tmp, PUBLISH_TAG_FILE), "w") as f:
                        json.dump({"dedupeKey": dedupe_key}, f)
                if watermark is not None:
                    with open(os.path.join(tmp, WATERMARK_FILE), "w") as f:
                        json.dump({"watermark": int(watermark)}, f)
                # POSIX rename onto an existing EMPTY directory: the
                # claimed placeholder becomes the complete save in one
                # atomic step.
                os.rename(tmp, final)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                try:
                    os.rmdir(final)  # release the claim
                except OSError:
                    pass  # surface the original failure, not the cleanup's
                raise
            if dedupe_key is not None:
                self._dedupe_keys[v] = dedupe_key
                self._dedupe_scanned.add(v)
            if watermark is not None:
                self._watermarks[v] = int(watermark)
            self._set_current(v)
            self._metrics.counter("publishes")
            if getattr(stage, "is_model_delta", False):
                self._metrics.counter("delta_publishes")
            else:
                self._metrics.counter("full_publishes")
            self._metrics.gauge("current_version", v)
            _log.info("published version %d to %s%s", v, self.root,
                      f" (key {dedupe_key!r})" if dedupe_key else "")
        self._notify()
        return v

    def rollback(self, version: int) -> int:
        """Repoint ``CURRENT`` at an existing ``version`` (no files are
        deleted — rolling forward again is another rollback)."""
        with self._lock:
            v = int(version)
            if v not in self.versions():
                raise ModelVersionNotFoundError(
                    f"version {v} not in registry {self.root} "
                    f"(has {self.versions()})"
                )
            self._set_current(v)
            self._metrics.counter("rollbacks")
            self._metrics.gauge("current_version", v)
        self._notify()
        return v

    # -- reads -------------------------------------------------------------
    def get(self, version: Optional[int] = None) -> Tuple[int, Any]:
        """Load ``(version, stage)`` — the current version by default.

        Loading goes through the standard reflective stage loader, so
        every model with a recorded content fingerprint is verified
        (:class:`~flinkml_tpu.io.read_write.ModelIntegrityError` on
        mismatch).

        When the version is an incremental delta, the chain is resolved
        here: walk ``base_version`` links down to a full snapshot, then
        apply upward verifying every fingerprint against the state it
        chains over — so the returned stage is always a complete,
        servable model, bitwise equal to a full-snapshot publish of the
        same trainer state. A pruned base or any fingerprint mismatch is
        a :class:`~flinkml_tpu.serving.errors.DeltaChainError` naming
        the broken link — never a silently wrong model."""
        v, stage = self._load_raw(version)
        if getattr(stage, "is_model_delta", False):
            stage = self._resolve_delta(v, stage)
            self._metrics.counter("delta_loads")
        self._metrics.counter("loads")
        return v, stage

    def _load_raw(self, version: Optional[int] = None) -> Tuple[int, Any]:
        """One version's stage exactly as persisted (deltas stay
        deltas)."""
        with self._lock:
            v = int(version) if version is not None else self.current_version()
            if v is None:
                raise ModelVersionNotFoundError(
                    f"registry {self.root} has no published versions"
                )
            path = self.path_of(v)
            if not os.path.exists(os.path.join(path,
                                               read_write.METADATA_FILE)):
                raise ModelVersionNotFoundError(
                    f"version {v} not in registry {self.root} "
                    f"(has {self.versions()})"
                )
        return v, read_write.load_stage(path)

    def _resolve_delta(self, version: int, delta: Any) -> Any:
        """Walk ``version``'s chain down to its full-snapshot base and
        apply every delta back up, fingerprint-verified at each link."""
        chain = [(version, delta)]  # target-first
        v, stage = version, delta
        while getattr(stage, "is_model_delta", False):
            base_v = stage.base_version
            try:
                base_v, base_stage = self._load_raw(base_v)
            except ModelVersionNotFoundError:
                raise DeltaChainError(
                    f"delta version {v} chains to base version {base_v}, "
                    f"which is not in registry {self.root} (pruned?); "
                    f"the chain for version {version} cannot be resolved"
                ) from None
            v, stage = base_v, base_stage
            if getattr(stage, "is_model_delta", False):
                chain.append((v, stage))
        base_version, model = v, stage
        if not (hasattr(model, "apply_delta")
                and hasattr(model, "delta_state")):
            raise DeltaChainError(
                f"delta chain for version {version} bottoms out at "
                f"version {base_version} ({type(model).__name__}), which "
                "is not delta-capable (no delta_state/apply_delta)"
            )
        fp = read_write.content_fingerprint(model.delta_state())
        prev_v = base_version
        for dv, d in reversed(chain):
            if d.base_fingerprint != fp:
                raise DeltaChainError(
                    f"delta version {dv} -> base {prev_v}: base "
                    f"fingerprint mismatch (delta expects "
                    f"{d.base_fingerprint[:12]}…, base state is "
                    f"{fp[:12]}…) — the chain for version {version} is "
                    "broken at this link"
                )
            model = model.apply_delta(d)
            fp = read_write.content_fingerprint(model.delta_state())
            if d.result_fingerprint != fp:
                raise DeltaChainError(
                    f"delta version {dv} applied on base {prev_v} does "
                    f"not reproduce its recorded result fingerprint "
                    f"({d.result_fingerprint[:12]}… != {fp[:12]}…) — the "
                    f"chain for version {version} is broken at this link"
                )
            prev_v = dv
        self._metrics.gauge("delta_chain_depth", len(chain))
        return model

    def delta_chain(self, base_version: int,
                    target_version: int) -> Optional[List[Any]]:
        """The ordered deltas that carry ``base_version`` to
        ``target_version``, or None when the target does not chain back
        to exactly that base (it IS the base, is a full snapshot, or
        chains past/around it). The serving engine's fast-swap probe:
        a non-None result means the active model can be patched in place
        with no full load."""
        try:
            v, stage = self._load_raw(target_version)
        except ModelVersionNotFoundError:
            return None
        chain: List[Any] = []
        while getattr(stage, "is_model_delta", False):
            chain.append(stage)
            base_v = stage.base_version
            if base_v == int(base_version):
                chain.reverse()
                return chain
            try:
                v, stage = self._load_raw(base_v)
            except ModelVersionNotFoundError:
                return None
        return None

    # -- freshness ---------------------------------------------------------
    def watermark_of(self, version: int) -> Optional[int]:
        """The source-batch watermark ``version`` was published with, or
        None for unstamped versions."""
        v = int(version)
        if v not in self._watermarks:
            try:
                with open(os.path.join(self.path_of(v),
                                       WATERMARK_FILE)) as f:
                    self._watermarks[v] = int(json.load(f)["watermark"])
            except (OSError, ValueError, KeyError):
                self._watermarks[v] = None
        return self._watermarks[v]

    def latest_watermark(self) -> Optional[int]:
        """The newest stamped watermark across all versions — the
        trainer-side edge the pool's freshness lag is measured
        against."""
        marks = [self.watermark_of(v) for v in self.versions()]
        marks = [m for m in marks if m is not None]
        return max(marks) if marks else None

    # -- change notification -----------------------------------------------
    def add_listener(self, callback: Callable[[int], None]) -> None:
        """Register ``callback(current_version)`` for publish/rollback
        events. Delivery is serialized and reads the CURRENT pointer at
        delivery time (concurrent publishes may coalesce into repeated
        notifications of the latest version, but a stale version can
        never be delivered after a newer one). Callbacks run in the
        publishing thread; an exception in one callback is reported as a
        warning (and a ``listener_errors`` counter) rather than unwinding
        into the publisher — the registry state is already committed."""
        self._listeners.append(callback)

    def remove_listener(self, callback: Callable[[int], None]) -> None:
        self._listeners.remove(callback)

    def _notify(self) -> None:
        with self._notify_lock:
            # Read the pointer INSIDE the delivery lock: every delivery
            # happens-after its read, so the last delivery in lock order
            # carries the newest pointer — out-of-order publish threads
            # cannot leave a follower on a stale version.
            version = self.current_version()
            for cb in list(self._listeners):
                try:
                    cb(version)
                except Exception as e:  # noqa: BLE001 — isolate listeners
                    self._metrics.counter("listener_errors")
                    warnings.warn(
                        f"registry listener {cb!r} failed for version "
                        f"{version}: {e!r} (registry state is committed; "
                        "the publishing thread continues)",
                        RuntimeWarning,
                        stacklevel=2,
                    )

    def _set_current(self, version: int) -> None:
        tmp = os.path.join(self.root, CURRENT_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(
                {"version": int(version),
                 "timestamp": int(time.time() * 1000)},
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, CURRENT_FILE))
