"""flinkml_tpu.serving — the online inference runtime.

The layer between the train/transform framework and "heavy traffic from
millions of users" (ROADMAP north star): a request path in front of the
fused pipeline executor, versioned model publication, and zero-downtime
model rollout. Four pieces:

- :class:`ServingEngine` — thread-safe ``predict()`` with **adaptive
  micro-batching**: concurrent requests coalesce into the power-of-two
  row buckets the fused compile cache already owns (per-bucket warmup at
  load, so steady state is zero-retrace), with bounded-queue admission
  control, per-request deadlines, and host-path load shedding.
- :class:`ModelRegistry` — versioned, fingerprint-verified model store
  with an atomic "current" pointer; ``publish`` / ``get`` / ``rollback``.
- :class:`SnapshotPublisher` — an ``IterationListener`` that turns a
  *running* training stream into registry versions every N epochs
  (mid-stream model emission, the reference's unbounded-``Iterations``
  capability).
- typed errors (:mod:`flinkml_tpu.serving.errors`) for every rejection
  the online path can produce.

See ``docs/operators/serving.md`` for lifecycle, knobs, and semantics,
and ``examples/serve_pipeline.py`` for the end-to-end
fit → publish → serve → hot-swap flow.
"""

from flinkml_tpu.serving.batcher import AdaptiveMicroBatcher, ServingRequest
from flinkml_tpu.serving.engine import (
    ServingConfig,
    ServingEngine,
    ServingResponse,
)
from flinkml_tpu.serving.errors import (
    EngineStoppedError,
    ModelIntegrityError,
    ModelVersionNotFoundError,
    RegistryError,
    ServingError,
    ServingOverloadError,
    ServingSchemaError,
    ServingTimeoutError,
)
from flinkml_tpu.serving.publisher import SnapshotPublisher
from flinkml_tpu.serving.registry import ModelRegistry

__all__ = [
    "AdaptiveMicroBatcher",
    "EngineStoppedError",
    "ModelIntegrityError",
    "ModelRegistry",
    "ModelVersionNotFoundError",
    "RegistryError",
    "ServingConfig",
    "ServingEngine",
    "ServingError",
    "ServingOverloadError",
    "ServingRequest",
    "ServingResponse",
    "ServingSchemaError",
    "ServingTimeoutError",
    "SnapshotPublisher",
]
