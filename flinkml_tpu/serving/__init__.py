"""flinkml_tpu.serving — the online inference runtime.

The layer between the train/transform framework and "heavy traffic from
millions of users" (ROADMAP north star): a request path in front of the
fused pipeline executor, versioned model publication, zero-downtime
model rollout, and a horizontally scaled replica-pool front. The pieces:

- :class:`ServingEngine` — thread-safe ``predict()`` with
  **continuous batching**: concurrent requests coalesce into the
  power-of-two row buckets the fused compile cache already owns,
  splitting at bucket boundaries so a late arrival joins the currently
  forming bucket (per-request row reassembly keeps responses bitwise
  single-version); per-bucket warmup at load, bounded-queue admission
  control, per-request deadlines swept promptly, and host-path load
  shedding. ``ServingConfig(batching="fifo")`` keeps PR 3's
  whole-request packing for comparison.
- :class:`ReplicaPool` + :class:`Router` — N engine replicas (one per
  device, or one per mesh slice time-sharing with training through
  ``local_execution_lock``) behind least-outstanding-rows routing with
  deadline-aware admission, per-replica overload degradation, automatic
  failover, and rolling (one-replica-at-a-time) registry hot-swaps.
- :class:`PoolAutoscaler` — the closed control loop over the pool's
  own metrics: hysteretic scale-up/-down (the autotune 1.10x
  decisive-win idiom), chaos replacement, compile-cache-warm scale-up
  replicas, and training slice-lease reclaim (FML304-audited).
- :class:`GrayFailGuard` + :class:`GrayFailPolicy` — gray-failure
  defense for the pool: per-dispatch deadlines with true abandonment,
  hedged requests (first completion wins, loser cancelled at the
  queue), MAD-based latency-outlier quarantine (the ``SLOW`` health
  state, canary-probed rejoin, autoscaler-composed replacement), and a
  brownout ladder shedding SLO classes in declared order under
  pool-wide degradation. See ``docs/development/fault_tolerance.md``.
- :class:`MultiModelPool` + :class:`SLOClass` — N registries over one
  pool with per-class deadline budgets and admission share caps
  (weighted admission: a batch job can never starve the interactive
  tier; refusals are the typed :class:`SLOAdmissionError`).
- :class:`ModelRegistry` — versioned, fingerprint-verified model store
  with an atomic "current" pointer; ``publish`` / ``get`` / ``rollback``.
- :class:`SnapshotPublisher` — an ``IterationListener`` that turns a
  *running* training stream into registry versions every N epochs
  (mid-stream model emission, the reference's unbounded-``Iterations``
  capability).
- typed errors (:mod:`flinkml_tpu.serving.errors`) for every rejection
  the online path can produce.

See ``docs/operators/serving.md`` for lifecycle, knobs, and semantics
(including the scale-out section), and ``examples/serve_pipeline.py``
for the end-to-end fit → publish → serve → hot-swap flow.
"""

from flinkml_tpu.serving.autoscaler import AutoscaleConfig, PoolAutoscaler
from flinkml_tpu.serving.batcher import (
    AdaptiveMicroBatcher,
    BatchSegment,
    ContinuousBatcher,
    ServingRequest,
)
from flinkml_tpu.serving.engine import (
    PendingPrediction,
    ServingConfig,
    ServingEngine,
    ServingResponse,
)
from flinkml_tpu.serving.grayfail import (
    GrayFailGuard,
    GrayFailPolicy,
    ReplicaQuarantinedError,
)
from flinkml_tpu.serving.errors import (
    DeltaChainError,
    EngineStoppedError,
    ModelIntegrityError,
    ModelVersionNotFoundError,
    PoolUnavailableError,
    RegistryError,
    ServingError,
    ServingMemoryError,
    ServingOverloadError,
    ServingSchemaError,
    ServingTimeoutError,
    SLOAdmissionError,
)
from flinkml_tpu.serving.health import HealthPolicy, ReplicaHealth, ReplicaState
from flinkml_tpu.serving.multiplex import (
    BATCH,
    INTERACTIVE,
    MultiModelPool,
    SLOClass,
)
from flinkml_tpu.serving.pool import Replica, ReplicaPool, slice_meshes
from flinkml_tpu.serving.publisher import SnapshotPublisher
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.serving.router import Router

__all__ = [
    "AdaptiveMicroBatcher",
    "AutoscaleConfig",
    "BATCH",
    "BatchSegment",
    "ContinuousBatcher",
    "DeltaChainError",
    "EngineStoppedError",
    "GrayFailGuard",
    "GrayFailPolicy",
    "HealthPolicy",
    "INTERACTIVE",
    "MultiModelPool",
    "PoolAutoscaler",
    "SLOAdmissionError",
    "SLOClass",
    "ModelIntegrityError",
    "ModelRegistry",
    "ModelVersionNotFoundError",
    "PoolUnavailableError",
    "RegistryError",
    "PendingPrediction",
    "Replica",
    "ReplicaHealth",
    "ReplicaPool",
    "ReplicaQuarantinedError",
    "ReplicaState",
    "Router",
    "ServingConfig",
    "ServingEngine",
    "ServingError",
    "ServingMemoryError",
    "ServingOverloadError",
    "ServingRequest",
    "ServingResponse",
    "ServingSchemaError",
    "ServingTimeoutError",
    "SnapshotPublisher",
    "slice_meshes",
]
